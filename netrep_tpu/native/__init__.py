"""Native C++ backend — ctypes bindings over the compute core in
``netstats.cpp`` (the rebuild's equivalent of the reference's
``src/netStats.cpp`` statistic kernels + ``src/permutations.cpp``
``PermutationProcedure`` over a thread pool, SURVEY.md §2.2,
BASELINE.json:5).

The JAX/XLA engine (:mod:`netrep_tpu.parallel.engine`) is the TPU compute
path; this backend is the native CPU tier: a threaded C++ permutation
procedure selectable via ``module_preservation(..., backend="native")``,
also serving as an independent (non-NumPy, non-JAX) parity oracle.

Determinism contract: permutation ``p`` (global index) derives its RNG from
``splitmix64(seed ^ f(p))`` inside the library, so results are invariant to
``n_threads`` and to how the permutation range is chunked across calls —
the property SURVEY.md §4 says tests must enforce.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable, Sequence

import numpy as np

from ..ops import oracle
from .build import ensure_built, toolchain_available

__all__ = [
    "available",
    "load_library",
    "NativeCore",
    "NativePermutationEngine",
]

_lib = None
_lib_lock = threading.Lock()

_F64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_I32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def available() -> bool:
    """True when the native backend can be used (compiler present or a
    cached build exists)."""
    import os

    from .build import lib_path

    return os.path.exists(lib_path()) or toolchain_available()


def load_library():
    """Build (if needed) and load the shared library; idempotent."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = ensure_built()
        lib = ctypes.CDLL(path)

        lib.nr_abi_version.restype = ctypes.c_int
        if lib.nr_abi_version() != 1:
            raise RuntimeError("native library ABI mismatch; delete "
                               f"{path} and rebuild")

        lib.nr_observed.restype = None
        lib.nr_observed.argtypes = [
            _F64, _F64, ctypes.c_void_p,            # tcorr, tnet, tdata(|0)
            ctypes.c_int, ctypes.c_int,             # n, s
            _I32, _I32, ctypes.c_int,               # idx_cat, sizes, n_mod
            _F64, _F64, ctypes.c_void_p,            # disc corr/deg/contrib(|0)
            _F64,                                   # out
        ]
        lib.nr_null.restype = ctypes.c_longlong
        lib.nr_null.argtypes = [
            _F64, _F64, ctypes.c_void_p,            # tcorr, tnet, tdata(|0)
            ctypes.c_int, ctypes.c_int,             # n, s
            _I32, ctypes.c_int,                     # pool, pool_size
            _I32, ctypes.c_int,                     # sizes, n_mod
            _F64, _F64, ctypes.c_void_p,            # disc corr/deg/contrib(|0)
            ctypes.c_longlong, ctypes.c_longlong,   # n_perm, perm_offset
            ctypes.c_ulonglong, ctypes.c_int,       # seed, n_threads
            _F64,                                   # nulls out
            ctypes.c_void_p,                        # progress (long long*)|0
            ctypes.c_void_p,                        # cancel (int*)|0
        ]
        lib.nr_props.restype = None
        lib.nr_props.argtypes = [
            _F64, _F64, ctypes.c_void_p,            # corr, net, data(|0)
            ctypes.c_int, ctypes.c_int,             # n, s
            _I32, ctypes.c_int,                     # idx, m
            _F64, _F64, _F64,                       # degree, contrib, profile
            ctypes.POINTER(ctypes.c_double),        # coherence
            ctypes.POINTER(ctypes.c_double),        # avg_weight
        ]
        _lib = lib
        return _lib


def _c(a: np.ndarray, dtype) -> np.ndarray:
    """Adopt ``a`` for the C ABI. Zero-copy when already C-contiguous with
    the right dtype (``ascontiguousarray`` returns the SAME object then) —
    the native analogue of the reference's no-copy Armadillo adoption of R
    matrices (SURVEY.md §2.2 "Zero-copy matrix adoption"); genome-scale
    float64 matrices are never duplicated. Other dtypes/layouts pay one
    conversion copy, which the C kernels require. Pinned by
    tests/test_native.py::test_zero_copy_adoption."""
    return np.ascontiguousarray(a, dtype=dtype)


def _opt_ptr(a: np.ndarray | None):
    """void* for an optional float64 array (NULL when absent)."""
    if a is None:
        return None
    return a.ctypes.data_as(ctypes.c_void_p)


class NativeCore:
    """Thin stateful wrapper holding one (discovery, test) problem in native
    layout: discovery per-module properties are precomputed once (the fixed
    side of every statistic, SURVEY.md §3.1) and concatenated for the C ABI."""

    def __init__(
        self,
        disc_corr: np.ndarray,
        disc_net: np.ndarray,
        disc_data: np.ndarray | None,
        test_corr: np.ndarray,
        test_net: np.ndarray,
        test_data: np.ndarray | None,
        modules: Sequence,          # ModuleSpec-likes: .disc_idx/.test_idx
        pool: np.ndarray,
    ):
        self.lib = load_library()
        self.test_corr = _c(test_corr, np.float64)
        self.test_net = _c(test_net, np.float64)
        self.with_data = disc_data is not None and test_data is not None
        self.test_data = (
            _c(test_data, np.float64) if self.with_data else None
        )
        self.n = self.test_corr.shape[0]
        self.s = self.test_data.shape[0] if self.with_data else 0
        self.pool = _c(pool, np.int32)
        self.sizes = np.asarray([len(m.test_idx) for m in modules], np.int32)
        self.n_mod = len(modules)
        self.obs_idx = _c(
            np.concatenate([np.asarray(m.test_idx) for m in modules]),
            np.int32,
        )

        # Discovery-side fixed properties via the NumPy oracle definitions
        # (identical math; computed once per pair, not in the hot loop)
        corr_cat, deg_cat, contrib_cat = [], [], []
        for m in modules:
            di = np.asarray(m.disc_idx)
            sub_corr = disc_corr[np.ix_(di, di)]
            sub_net = disc_net[np.ix_(di, di)]
            corr_cat.append(np.asarray(sub_corr, np.float64).ravel())
            deg_cat.append(oracle.weighted_degree(sub_net))
            if self.with_data:
                contrib_cat.append(
                    oracle.node_contribution(disc_data[:, di])
                )
        self.disc_corr_cat = _c(np.concatenate(corr_cat), np.float64)
        self.disc_deg_cat = _c(np.concatenate(deg_cat), np.float64)
        self.disc_contrib_cat = (
            _c(np.concatenate(contrib_cat), np.float64)
            if self.with_data else None
        )

    def observed(self) -> np.ndarray:
        out = np.empty((self.n_mod, oracle.N_STATS), np.float64)
        self.lib.nr_observed(
            self.test_corr, self.test_net, _opt_ptr(self.test_data),
            self.n, self.s, self.obs_idx, self.sizes, self.n_mod,
            self.disc_corr_cat, self.disc_deg_cat,
            _opt_ptr(self.disc_contrib_cat), out,
        )
        return out

    def null(
        self,
        n_perm: int,
        seed: int = 0,
        perm_offset: int = 0,
        n_threads: int = 0,
        out: np.ndarray | None = None,
        progress_buf: np.ndarray | None = None,
        cancel_buf: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int]:
        """Run permutations [perm_offset, perm_offset + n_perm) of stream
        ``seed``. Returns ``(nulls, completed)``."""
        if out is None:
            out = np.empty((n_perm, self.n_mod, oracle.N_STATS), np.float64)
        done = self.lib.nr_null(
            self.test_corr, self.test_net, _opt_ptr(self.test_data),
            self.n, self.s, self.pool, self.pool.size,
            self.sizes, self.n_mod,
            self.disc_corr_cat, self.disc_deg_cat,
            _opt_ptr(self.disc_contrib_cat),
            n_perm, perm_offset,
            np.uint64(int(seed) & 0xFFFFFFFFFFFFFFFF), n_threads, out,
            _opt_ptr(progress_buf), _opt_ptr(cancel_buf),
        )
        if done < 0:
            raise ValueError("module sizes exceed the candidate pool")
        return out, int(done)


class NativePermutationEngine:
    """Interface-compatible counterpart of
    :class:`netrep_tpu.parallel.engine.PermutationEngine` backed by the C++
    core, so ``module_preservation(backend='native')`` can swap it in.

    The permutation range is dispatched to the library in chunks so Python
    regains control between calls — KeyboardInterrupt lands between chunks
    (the reference's cooperative Ctrl-C path, SURVEY.md §5) and partial
    nulls are kept / checkpointable exactly like the JAX engine's.
    """

    def __init__(
        self,
        disc_corr, disc_net, disc_data,
        test_corr, test_net, test_data,
        modules, pool,
        config=None,
        mesh=None,  # accepted for signature parity; meaningless on CPU
        n_threads: int = 0,
    ):
        del mesh
        # The bf16 screened fast-pass (ISSUE 16) is a JAX-engine feature;
        # this backend is exact f32/f64 throughout. 'auto' means f32 here,
        # an explicit ask refuses.
        if getattr(config, "null_precision", "auto") == "bf16_rescue":
            raise ValueError(
                "null_precision='bf16_rescue' is not supported on "
                "backend='native'; use 'auto' or 'f32'"
            )
        self.core = NativeCore(
            np.asarray(disc_corr), np.asarray(disc_net),
            None if disc_data is None else np.asarray(disc_data),
            np.asarray(test_corr), np.asarray(test_net),
            None if test_data is None else np.asarray(test_data),
            modules, np.asarray(pool),
        )
        self.modules = list(modules)
        self.pool = self.core.pool          # checkpoint fingerprint fields
        self.has_data = self.core.with_data
        self.chunk = max(
            64, int(getattr(config, "chunk_size", 1024) or 1024)
        )
        self.n_threads = n_threads

    def observed(self) -> np.ndarray:
        return self.core.observed()

    # -- hooks consumed by engine.run_checkpointed_chunks ------------------

    def prepare_key(self, key) -> int:
        if not isinstance(key, (int, np.integer)):
            raise TypeError(
                "backend='native' takes an integer seed, got "
                f"{type(key).__name__}; jax PRNG keys only apply to the "
                "default backend='jax'"
            )
        # mask to the counter-based generator's 64-bit seed space (matches
        # core.null) so negative seeds round-trip through checkpoints
        return int(key) & 0xFFFFFFFFFFFFFFFF

    def key_data(self, key) -> np.ndarray:
        """RNG-stream identity stored in checkpoints: (engine kind, seed).
        Distinct from the JAX engine's jax.random key data, so resuming a
        JAX checkpoint on the native backend (different null samples) is
        refused rather than spliced."""
        return np.asarray(
            [0x6E61746976, int(key) & 0xFFFFFFFFFFFFFFFF], dtype=np.uint64
        )

    #: tells run_checkpointed_chunks to clamp the final chunk to the exact
    #: remaining count — no static-shape constraint here, unlike XLA
    dynamic_chunk = True

    def effective_chunk(self) -> int:
        return self.chunk

    def perm_keys(self, key: int, start: int, count: int):
        # the native RNG is counter-based on the global permutation index;
        # the "keys" for a chunk are just its (seed, offset, count) triple
        return (int(key), int(start), int(count))

    def fingerprint_arrays(self):
        c = self.core
        return [c.test_corr, c.test_net, c.test_data,
                c.disc_corr_cat, c.disc_deg_cat, c.disc_contrib_cat]

    def run_null(
        self,
        n_perm: int,
        key: int = 0,
        progress: Callable[[int, int], None] | None = None,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
        fault_policy=None,
        observed=None,  # signature parity with the JAX engine; always exact
    ) -> tuple[np.ndarray, int]:
        del observed
        # reuse the single chunked/interruptible/checkpointable loop shared
        # with the JAX engines (engine.run_checkpointed_chunks) so the
        # interrupt/resume semantics cannot drift across backends
        from ..parallel.engine import run_checkpointed_chunks

        def fn(spec):
            seed, start, count = spec
            out, completed = self.core.null(
                count, seed=seed, perm_offset=start, n_threads=self.n_threads
            )
            if completed < count:  # cancelled mid-chunk (cooperative flag)
                out[completed:] = np.nan
            return out

        def write(nulls, out, done, take):
            nulls[done:done + take] = out[:take]

        return run_checkpointed_chunks(
            self, n_perm, key, fn,
            (n_perm, self.core.n_mod, oracle.N_STATS), write,
            progress=progress, checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, fault_policy=fault_policy,
        )
