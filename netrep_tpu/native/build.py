"""Build machinery for the native C++ compute core.

Compiles ``netstats.cpp`` with the system ``g++`` into a shared object the
first time it is needed, keyed by a hash of the source so edits invalidate
the cache automatically. Mirrors the role of the reference's ``src/Makevars``
build config (SURVEY.md §2.2 "Build config") without requiring users to run
a build step: the library is built lazily on first use and cached under the
package directory.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_HERE, "netstats.cpp")

CXX = os.environ.get("NETREP_CXX", "g++")


def _default_march() -> str:
    """Arch level for the lazy build. AVX2 (haswell) when the host has it:
    the hot loops (power iteration, gram/degree reductions) are dense double
    FMAs, and AVX2 measured +27% over the flagless baseline at the Config B
    shape — while -march=native (→ cooperlake on the bench VM) measured ~25%
    SLOWER than AVX2 from its AVX-512 codegen. Hosts without AVX2 keep the
    portable flagless baseline ('' → no -march flag), so a host's default
    build never carries instructions weaker siblings sharing the package
    dir might lack, and non-x86 toolchains never see an -march they could
    reject."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags") and "avx2" in line.split():
                    # haswell == AVX2+FMA and is accepted by gcc >= 4.9 /
                    # clang >= 3.6; x86-64-v3 would need gcc >= 11 and
                    # measured identically (15.21 vs 15.17 perms/s)
                    return "haswell"
    except OSError:
        pass
    return ""


#: NETREP_CXX_MARCH overrides the arch level; empty/unset-able — an empty
#: string omits the flag entirely (the portable baseline build).
_MARCH = os.environ.get("NETREP_CXX_MARCH", _default_march())

CXXFLAGS = [
    "-O3",
    "-std=c++17",
    "-shared",
    "-fPIC",
    "-pthread",
    "-fno-math-errno",
    "-funroll-loops",
    *([f"-march={_MARCH}"] if _MARCH else []),
]


def _source_tag() -> str:
    """Cache key of the lazy build: source bytes AND the flag set — a flag
    change must rebuild even when the source is unchanged."""
    h = hashlib.sha256()
    with open(SOURCE, "rb") as f:
        h.update(f.read())
    h.update("\0".join([CXX, *CXXFLAGS]).encode())
    return h.hexdigest()[:12]


def lib_path() -> str:
    return os.path.join(_HERE, f"_netstats_{_source_tag()}.so")


def toolchain_available() -> bool:
    try:
        subprocess.run(
            [CXX, "--version"], capture_output=True, check=True, timeout=30
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def ensure_built() -> str:
    """Compile the shared object if the cached build is missing; return its
    path. Raises ``RuntimeError`` with the compiler output on failure."""
    path = lib_path()
    if os.path.exists(path):
        return path
    # build into a temp file then atomically rename, so concurrent importers
    # (e.g. pytest-xdist workers) never load a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        proc = subprocess.run(
            [CXX, *CXXFLAGS, SOURCE, "-o", tmp],
            capture_output=True,
            text=True,
            timeout=300,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed ({CXX} exit {proc.returncode}):\n"
                f"{proc.stderr}"
            )
        os.replace(tmp, path)
        # NOTE: other _netstats_*.so variants are deliberately left in
        # place — different flag sets (hosts sharing a package dir, march
        # overrides) cache as coexisting variants, and unlinking a sibling
        # would race a concurrent process between its ensure_built() and
        # CDLL. The files are small and gitignored.
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path
