"""`netrep serve` core: job queue + scheduler + multi-tenant state (ISSUE 7).

The always-on service the ROADMAP's "millions of users" north star needs:
tenants register datasets ONCE, then submit many preservation requests
against them; the scheduler re-buckets modules from different queued
requests into shared module-size-bucket dispatches
(:mod:`netrep_tpu.serve.packer`), runs them on warm pooled engines
(:mod:`netrep_tpu.serve.pool`), and returns per-request results
bit-identical to stand-alone ``module_preservation()`` calls.

Scheduling policy:

- **admission control**: a bounded per-tenant queue; a submit over the
  bound is rejected immediately (``request_rejected`` event +
  :class:`QueueFull`) — backpressure, not unbounded latency;
- **weighted round-robin across tenants**: each tenant appears
  ``weight`` times in the scheduling ring, so a heavy tenant cannot
  starve a light one;
- **oldest-deadline-first within a tenant**: requests carry a deadline
  (submit time + ``slo_s`` unless given explicitly); the tenant's most
  urgent request seeds each pack;
- **opportunistic packing**: the seed request's pack key (dataset-pair
  digest + engine-config identity) pulls compatible requests from EVERY
  tenant's queue — cross-request, cross-tenant shared dispatches — up to
  ``max_pack``;
- **SLO mechanism**: each packed request retires at its own ``n_perm``
  ceiling (and by its own stop rule when adaptive) via the engine's
  retirement re-bucketing, so a cheap request never waits for the pack's
  deepest member (:class:`~netrep_tpu.serve.packer.PackMonitor`);
- **fault isolation**: every pack runs under the PR 4/6 fault ladder
  (``fault_policy``); a failed pack is split and its members re-queued
  solo, so one tenant's poisoned request (or a device loss mid-pack)
  fails alone — the queue and the other tenants' work survive.

Crash-safe serving (ISSUE 10) extends the policy above with the
durability the engine layer already has:

- **write-ahead journal** (:mod:`netrep_tpu.serve.journal`): every
  admission is an fsynced ``accepted`` record before it enters the
  queue, every completion a ``done``/``failed`` record — ``--recover``
  replays the journal, re-registers datasets, answers duplicates from
  journaled results, and re-queues unfinished work in original order,
  resuming partial packs from per-pack checkpoints bit-identically;
- **idempotency keys**: a duplicate submission with a seen key attaches
  to the in-flight request or returns the completed result
  (``request_deduped``) — client retry-with-backoff is safe by
  construction;
- **deadline enforcement**: expired requests are cancelled at pack
  boundaries via the same ``force_retire`` retirement re-bucketing a
  statistical decision takes (``request_expired``; survivors unaffected);
- **brownout load shedding**: past an estimated backlog drain time the
  server sheds the newest requests of the lowest-weight tenants with a
  ``retry_after_s`` hint (``serve_brownout_enter``/``exit``) instead of
  hitting the ``QueueFull`` cliff.

The whole ops surface is the telemetry bus: a server-lifetime
``serve_start``/``serve_end`` span, per-request
``request_received``/``request_done`` spans (latency = span duration),
``request_packed``/``request_rejected`` point events with per-tenant
labels, and Prometheus exposition (:meth:`PreservationServer
.metrics_text`) with per-tenant labeled series.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import threading
import time
import uuid
from typing import Sequence

import numpy as np

logger = logging.getLogger("netrep_tpu")

from ..models import dataset as ds
from ..models.preservation import _overlap_setup
from ..ops import pvalues as pv
from ..utils import telemetry as tm
from ..utils.checkpoint import content_digest
from ..utils.config import EngineConfig
from ..utils.faults import SimulatedCrash, resolve_runtime
from . import journal as jnl
from .packer import (
    GridPackedEngine, PackedEngine, PackMonitor, RequestPlan, assign_bases,
    run_pack,
)
from .pool import ProgramPool


class ServeError(RuntimeError):
    """A request failed (validation, execution, or unknown tenant/dataset)."""


class QueueFull(ServeError):
    """Admission control rejected the request: the tenant's queue is at
    its bound (or the service is in a brownout and shedding load) — back
    off and retry. ``retry_after_s`` (ISSUE 10), when the server can
    estimate its backlog drain time, is the client's hint for WHEN —
    predictable shedding instead of a hard cliff."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class ServeConfig:
    """Service knobs (transport-independent).

    ``engine`` is the ONE :class:`EngineConfig` every served run uses —
    pack compatibility requires a shared chunk size and kernel
    configuration, and bit-parity with a direct call requires the caller
    to use the same config. ``autotune=False`` by default so the serving
    path is deterministic run-to-run.
    """

    max_queue: int = 64
    max_pack: int = 4
    pool_size: int = 8
    #: batching window: a pack below ``max_pack`` waits this long for
    #: compatible stragglers before dispatching — tiny against a request's
    #: service time, decisive for pack formation under concurrent arrivals
    #: (0 = dispatch immediately; the load generator uses ~0.1 s)
    pack_window_s: float = 0.0
    engine: EngineConfig = dataclasses.field(
        default_factory=lambda: EngineConfig(chunk_size=64, autotune=False)
    )
    default_n_perm: int | None = None
    null: str = "overlap"
    background_label: str = "0"
    slo_s: float = 60.0
    fault_policy: object = None
    telemetry: object = None
    # -- crash-safe serving (ISSUE 10) ----------------------------------
    #: write-ahead journal path; None = journaling off (behavior-identical
    #: to PR 7 serving — no fsyncs, no dedup map persistence)
    journal: str | None = None
    #: replay ``journal`` on boot: re-register datasets, load completed
    #: results into the idempotency map, re-queue accepted-but-unfinished
    #: requests in original order (``serve --recover``)
    recover: bool = False
    #: per-pack checkpoint directory; default (None) derives
    #: ``<journal>.ckpt`` when journaling is on, so a SIGKILL mid-pack
    #: resumes from the last chunk boundary instead of recomputing
    checkpoint_dir: str | None = None
    #: chunk-boundary checkpoint cadence for packed runs (permutations)
    checkpoint_every: int = 4096
    #: enforce request deadlines (submit + slo_s, or the explicit
    #: ``deadline_s``): expired requests are cancelled at pack boundaries
    #: via retirement re-bucketing (``request_expired``); False restores
    #: the PR 7 sort-key-only semantics
    enforce_deadlines: bool = True
    #: brownout admission control: when the estimated backlog drain time
    #: exceeds this, the server sheds new load from the lowest-weight
    #: tenants with a ``retry_after_s`` hint; None disables (PR 7
    #: behavior). Exit at ``brownout_exit_s`` (default: half of enter —
    #: hysteresis so the state cannot flap every submit)
    brownout_enter_s: float | None = None
    brownout_exit_s: float | None = None
    #: assumed steady-state throughput (perms/s) before the server has
    #: measured its own; falls back to the perf ledger's serve history,
    #: else brownout stays off until a measurement exists
    brownout_rate_pps: float | None = None
    #: optional brownout degradation: cap admitted requests' n_perm at
    #: this while browned out (EXPLICITLY changes results — an opt-in
    #: graceful-degradation knob, off by default)
    brownout_nperm_cap: int | None = None
    #: completed requests kept in the in-memory idempotency map (oldest
    #: evicted beyond this; in-flight requests never evict) — a duplicate
    #: of an evicted key recomputes, deterministically, to the same result
    idem_cache: int = 4096
    # -- SLO burn rate (ISSUE 13) ---------------------------------------
    #: error budget: the tolerated fraction of requests missing their
    #: ``slo_s`` inside the sliding window; burn rate = observed miss
    #: fraction / budget (1.0 = burning the budget exactly, >1 = on fire)
    slo_budget: float = 0.01
    #: sliding window (seconds) the burn rate is computed over
    slo_window_s: float = 300.0
    # -- fleet serving (ISSUE 14) ---------------------------------------
    #: replica identity inside a ``serve --fleet`` deployment (e.g.
    #: ``"r0"``). When set, the replica's FIRST completed pack records
    #: its cold-start compile span to the perf ledger under a
    #: fleet-labeled fingerprint (``serve-fleet-coldstart|<label>|...``)
    #: — the measured baseline the ROADMAP's AOT warm-start goal has to
    #: beat. None = stand-alone server, nothing recorded.
    fleet_label: str | None = None
    # -- AOT warm start (ISSUE 15) --------------------------------------
    #: boot-time preload: after a ``--recover`` replay re-registered
    #: datasets, a single bounded background thread builds the warm-pool
    #: engine for up to ``preload_max`` registered (discovery, test)
    #: pairs and acquires their programs through the AOT store — a
    #: populated store then answers the first request at steady-state
    #: speed (``compile_span ~0``, ``source: aot``). False = PR 14 boot.
    preload_aot: bool = True
    preload_max: int = 4
    #: export programs this server had to jit-compile into the AOT store
    #: (so the NEXT boot — or a respawned fleet peer — loads them).
    #: None = auto: on exactly when ``fleet_label`` is set (fleet
    #: replicas self-warm the shared store); True/False force it.
    aot_export: bool | None = None
    # -- cross-pair packing (ISSUE 17) ----------------------------------
    #: widen the pack key from the (discovery, test) pair to the TEST
    #: dataset + permutation-pool signature: requests testing DIFFERENT
    #: cohorts' modules in the same test cohort then share one dispatch
    #: stream (:class:`~netrep_tpu.serve.packer.GridPackedEngine`) — the
    #: grid-column workload. Results stay bit-identical to solo calls
    #: (per-request discovery props + the two-identity contract); only
    #: applies to single-test dense requests (data-only pairs keep the
    #: pairwise key). Off by default: the pack key stays pairwise.
    cross_pair_packing: bool = False


@dataclasses.dataclass
class Request:
    """One queued analyze request (in-process handle; the transports wrap
    it). ``done`` fires when ``result`` or ``error`` is set."""

    id: str
    tenant: str
    discovery: str
    test: object           # str, or list[str] for the multi-test path
    seed: int
    adaptive: bool
    plan: object           # RequestPlan (single) or _MultiPlan
    pack_key: object       # None = never packed (multi-test / solo-only)
    deadline: float
    submitted_m: float
    seq: int
    sid: str | None = None          # telemetry span id
    #: distributed-trace identity (ISSUE 13): the client-minted (or
    #: server-assigned) trace id + the caller's parent span id — stamped
    #: on the request's telemetry span, journaled with ``accepted``, and
    #: stable across a ``--recover`` restart so the request's span
    #: subtree is one trace across process generations
    trace: str | None = None
    trace_parent: str | None = None
    solo_only: bool = False
    #: durable identity in the write-ahead journal (ISSUE 10): the
    #: client-supplied idempotency key, or an auto-assigned one; stable
    #: across restarts (recovery re-queues under the original key)
    journal_key: str | None = None
    #: set when a bounded drain journaled this request as
    #: ``drain_requeued`` instead of finishing it (ISSUE 19): the work
    #: migrates with the journal handoff, so a fleet caller retries the
    #: SAME idempotency key at the adopting peer rather than surfacing
    #: the drain as a client error
    requeued_on_drain: bool = False
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    result: dict | None = None
    error: str | None = None


@dataclasses.dataclass
class _MultiPlan:
    """Plan of a multi-test request (one discovery vs T cohorts sharing a
    node universe) — served through the MultiTestEngine T-axis."""

    plan: RequestPlan               # specs/pool/budget (shared across T)
    test_names: list[str]


class _Tenant:
    def __init__(self, name: str, weight: int):
        self.name = name
        self.weight = max(1, int(weight))
        self.datasets: dict[str, _Dataset] = {}
        self.pending: list[Request] = []
        self.counters = {
            "received": 0, "done": 0, "failed": 0, "rejected": 0,
            "expired": 0, "deduped": 0,
        }
        # -- per-tenant observability rollups (ISSUE 13) -----------------
        #: request latency over the PINNED bucket boundaries — the
        #: p50/p99 source of `top`, `stats`, and the Prometheus
        #: histogram exposition
        self.lat_hist = tm.BucketHistogram(tm.LATENCY_BUCKETS_S)
        #: attributed device-seconds per request, same pinned-bucket
        #: contract
        self.cost_hist = tm.BucketHistogram(tm.COST_BUCKETS_S)
        #: attributed cost totals folded from each request_cost
        self.cost = {"device_s": 0.0, "transfer_s": 0.0, "perms": 0,
                     "bytes_to_host": 0, "compile_s_amortized": 0.0}
        #: (monotonic_t, missed_slo) per terminal request — the SLO
        #: burn-rate sliding window
        self.slo_marks: list[tuple[float, bool]] = []
        #: hysteresis latch for the ``slo_burn`` anomaly detector
        #: (ISSUE 20): fire once when the burn rate first crosses 1.0,
        #: re-arm only after it drops back under budget
        self.burn_flagged = False


class _Dataset:
    def __init__(self, name: str, dataset, assignments, digest: str,
                 beta=None):
        self.name = name
        self.ds = dataset              # models.dataset.Dataset
        self.assignments = assignments  # normalized {node: label} or None
        self.digest = digest
        #: data-only derivation spec (ISSUE 9 atlas tenants): the
        #: soft-threshold β / (β, kind) the engines derive submatrices
        #: with; None = a dense registration with stored matrices
        self.beta = beta


class PreservationServer:
    """The in-process serving core — what the unix-socket daemon wraps and
    what :class:`netrep_tpu.serve.client.InProcessClient` (and the tier-1
    tests) drive directly."""

    def __init__(self, config: ServeConfig | None = None, start: bool = True):
        self.config = config or ServeConfig()
        self.tel, self._tel_owned = tm.resolve_arg(self.config.telemetry)
        self._fault = resolve_runtime(self.config.fault_policy)
        self._work = threading.Condition()
        self._tenants: dict[str, _Tenant] = {}
        self._tenant_order: list[str] = []
        self._rr: list[str] = []       # weighted ring (name x weight)
        self._rr_pos = 0
        self._seq = 0
        self._pack_seq = 0
        self._inflight = 0
        self._accepting = True
        self._stop = False
        self._started_m = time.monotonic()
        self.pool = ProgramPool(self.config.pool_size)
        self._engine_cfg_id = repr(self.config.engine)
        # -- crash-safe serving state (ISSUE 10) --------------------------
        #: idempotency map: journal key -> Request (in-flight requests are
        #: attached to; completed ones answer duplicates from their result)
        self._idem: dict[str, Request] = {}
        #: completed keys in retirement order (bounds the map's memory)
        self._idem_done: list[str] = []
        self._replaying = False
        self._adopting = False
        self._coldstart_done = False
        self._fixture_depth = 0
        self._last_drain_requeued = 0
        #: autoscaler idle signal (ISSUE 19): wall clock of the last
        #: accepted or finished request — `stats()['idle_s']`
        self._last_active_m = self._started_m
        self._brownout = False
        self._served_perms = 0.0     # measured steady-state rate inputs
        self._busy_s = 0.0
        self.journal: jnl.RequestJournal | None = None
        self._ckpt_dir = self.config.checkpoint_dir
        if self.config.journal:
            if self._ckpt_dir is None:
                self._ckpt_dir = self.config.journal + ".ckpt"
            self.journal = jnl.RequestJournal(self.config.journal)
        self._serve_sid = None
        if self.tel is not None:
            self._serve_sid = self.tel.begin_span(
                "serve_start", max_queue=self.config.max_queue,
                max_pack=self.config.max_pack,
                pool_size=self.config.pool_size,
                journal=bool(self.journal),
            )
        self._worker: threading.Thread | None = None
        self._preload_thread: threading.Thread | None = None
        if self.config.recover and self.config.journal:
            self._recover()
        if self.config.preload_aot:
            self._start_preload()
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._worker is not None:
            return
        self._worker = threading.Thread(
            target=self._loop, name="netrep-serve-worker", daemon=True
        )
        self._worker.start()

    def close(self, drain: bool = True, timeout: float | None = None) -> None:
        """Graceful shutdown: stop accepting, optionally finish every
        queued request (the SIGTERM drain protocol), stop the worker,
        release pooled engines, close the telemetry span/bus.

        ``timeout`` bounds the drain (ISSUE 10): queued work that cannot
        finish in time is NOT dropped silently — with a journal attached
        its keys are recorded as ``drain_requeued`` (they are already
        ``accepted``-but-unfinished, so the next ``--recover`` boot picks
        them up) and each local waiter is unblocked with a distinctive
        error naming the journaled restart path."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._work:
            self._accepting = False
            self._work.notify_all()
        if drain and self._worker is not None:
            with self._work:
                while (self._inflight or self._any_pending_locked()):
                    if deadline is not None and time.monotonic() > deadline:
                        break
                    self._work.wait(0.25)
        with self._work:
            self._stop = True
            remainder = [
                r for t in self._tenants.values() for r in t.pending
            ]
            for t in self._tenants.values():
                t.pending.clear()
            self._work.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10.0)
            self._worker = None
        with self._work:
            pt, self._preload_thread = self._preload_thread, None
        if pt is not None:
            # the preload thread is short-lived and daemon; the drain
            # waits for it so the thread set returns to baseline
            pt.join(timeout=60.0)
        requeued = self._last_drain_requeued = len(remainder)
        if remainder:
            if self.journal is not None:
                self.journal.append(
                    "drain_requeued",
                    keys=[r.journal_key for r in remainder],
                )
            for r in remainder:
                if self.journal is not None:
                    r.requeued_on_drain = True
                    r.error = (
                        "drain timeout: request journaled as requeued-"
                        "on-restart (serve --recover completes it)"
                    )
                else:
                    r.error = ("drain timeout: request dropped "
                               "(no journal)")
                r.done.set()
        self.pool.clear()
        if self.tel is not None:
            done = sum(t.counters["done"] for t in self._tenants.values())
            fail = sum(t.counters["failed"] for t in self._tenants.values())
            self.tel.end_span(
                self._serve_sid, "serve_end", drained=bool(drain),
                requests_done=done, requests_failed=fail,
                requests_requeued=requeued,
                s=time.monotonic() - self._started_m,
                **self.pool.stats(),
            )
            if self._tel_owned:
                self.tel.close()
        if self.journal is not None:
            self.journal.close()

    # -- restart recovery (ISSUE 10) ---------------------------------------

    # -- AOT warm start (ISSUE 15) ----------------------------------------

    def _aot_export_scope(self):
        """Context manager enabling AOT export-on-miss around a pack run:
        programs this server had to jit-compile are serialized into the
        store, so the next boot (or a respawned fleet peer) loads them
        instead of compiling. Auto mode exports exactly on fleet
        replicas (``fleet_label`` set)."""
        import contextlib

        from ..utils import aot

        export = self.config.aot_export
        if export is None:
            export = self.config.fleet_label is not None
        store = aot.get_store() if export else None
        return store.exporting() if store is not None \
            else contextlib.nullcontext()

    def _start_preload(self) -> None:
        """Boot-time AOT preload (ISSUE 15): for up to ``preload_max``
        registered (discovery, test) pairs — a ``--recover`` replay or a
        fleet journal adoption just re-registered them — build the
        warm-pool engine and acquire its programs through the AOT store
        on ONE background thread, so a populated store's deserialize +
        cached-compile happens before the first request, not inside it.
        Best-effort by construction: every failure leaves the ordinary
        lazy path intact."""
        with self._work:
            if self._preload_thread is not None:
                return
            pairs = []
            for ten in self._tenants.values():
                discs = [d for d in ten.datasets.values()
                         if d.assignments is not None]
                tests = list(ten.datasets.values())
                for d in discs:
                    for t in tests:
                        if t.name != d.name:
                            pairs.append((d, t))
            pairs = pairs[:max(0, int(self.config.preload_max))]
        if not pairs:
            return

        def preload(pairs=tuple(pairs), pool=self.pool):
            for d, t in pairs:
                try:
                    plan = self._build_plan(
                        d, t, None, n_perm=self.config.default_n_perm,
                        seed=0, alternative="greater", adaptive=False,
                        rule=None,
                    )
                    plan.base = 0
                    key = self._pool_key("packed", (d.digest, t.digest),
                                         [plan])
                    engine, _hit = pool.get(
                        key, lambda: self._pack_engine(d, t, [plan])
                    )
                    # acquire (and, on a warm store, deserialize +
                    # cache-compile) the chunk program; run the observed
                    # pass once so the pooled engine is request-ready
                    engine._chunk_fn()
                    engine.observed()
                # netrep: allow(exception-taxonomy) — boot-time warmup is an optimization pass: any failure (unregistered pair shape, store I/O, OOM-scale plan) must leave the lazy path to serve the request as before
                except Exception:
                    logger.debug("AOT preload skipped one pair",
                                 exc_info=True)

        t = threading.Thread(target=preload, name="netrep-aot-preload",
                             daemon=True)
        with self._work:
            self._preload_thread = t
        t.start()

    def _recover(self) -> None:
        """Replay the write-ahead journal on boot (``serve --recover``):
        re-register tenants and dataset references, load completed (and
        terminally failed) requests into the idempotency map so
        duplicates are answered without recomputing, and re-queue every
        accepted-but-unfinished request in original ``seq`` order —
        combined with the per-pack checkpoints, a killed server resumes
        to results bit-identical to an uninterrupted one."""
        self._replay_journal(self.config.journal, quiet=True)

    def adopt_journal(self, path: str, *,
                      datasets_only: bool = False) -> dict | None:
        """Replay a FOREIGN journal into this live server — the fleet
        failover path (ISSUE 14): the coordinator hands the survivor its
        dead peer's shipped journal copy, and the survivor re-registers
        the peer's tenants/datasets, loads its completed results into
        the idempotency map, and re-queues its unfinished requests.

        Unlike boot recovery, the adopted records are NOT already in
        this server's own journal, so re-queued requests go through the
        ordinary journaling path (``quiet=False``): each adopted pending
        request lands as a fresh fsynced ``accepted`` record here —
        durable against a second failure. Admission bounds are bypassed
        like boot recovery (the work was admitted once, on the peer).
        Completed results stay in the in-memory map only; a duplicate
        arriving after yet another restart recomputes, deterministically,
        to the same answer. Returns the replay summary (or None when the
        journal does not exist).

        ``datasets_only`` replays registrations but neither results nor
        pendings — the seeding mode for a freshly SPAWNED replica
        (ISSUE 19) adopting a *live* peer's shipped copy: the newcomer
        must know every tenant/dataset before the ring routes to it,
        but the peer's requests are the peer's to finish."""
        return self._replay_journal(path, quiet=False,
                                    datasets_only=datasets_only)

    def _replay_journal(self, path: str, *, quiet: bool,
                        datasets_only: bool = False) -> dict | None:
        """Shared journal-replay core of ``--recover`` (``quiet=True``:
        the records already live in our own journal — do not re-journal)
        and :meth:`adopt_journal` (``quiet=False``)."""
        from .protocol import decode_arrays

        if not path or not os.path.exists(path):
            return None
        state = jnl.scan(path)
        self._replaying = quiet
        self._adopting = not quiet
        try:
            for name, weight in state["tenants"].items():
                self.register_tenant(name, weight)
            for rec in state["datasets"]:
                pl = rec.get("payload") or {}
                if rec.get("form") == "fixture":
                    self.register_fixture(
                        str(rec["tenant"]), str(pl.get("prefix", "fx")),
                        genes=int(pl["genes"]), modules=int(pl["modules"]),
                        n_samples=int(pl["n_samples"]),
                        seed=int(pl["seed"]),
                    )
                else:
                    beta = pl.get("beta")
                    self.register_dataset(
                        str(rec["tenant"]), str(rec["name"]),
                        network=(np.asarray(pl["network"], dtype=np.float64)
                                 if pl.get("network") is not None else None),
                        correlation=(
                            np.asarray(pl["correlation"], dtype=np.float64)
                            if pl.get("correlation") is not None else None),
                        data=(np.asarray(pl["data"], dtype=np.float64)
                              if pl.get("data") is not None else None),
                        assignments=pl.get("assignments"),
                        beta=tuple(beta) if isinstance(beta, list) else beta,
                    )
            # terminal records -> idempotency map: a duplicate of a
            # completed request gets the journaled result, of a failed
            # one its error — never a recompute
            for key, rec in ({} if datasets_only
                             else state["results"]).items():
                acc = state["accepted"].get(key) or {}
                req = self._terminal_request(key, rec, acc)
                req.result = decode_arrays(rec.get("result") or {})
                req.done.set()
                self._idem[key] = req
                self._retire_idem(req)
            for key, rec in ({} if datasets_only
                             else state["failed"]).items():
                acc = state["accepted"].get(key) or {}
                req = self._terminal_request(key, rec, acc)
                req.error = str(rec.get("error", "failed before restart"))
                req.done.set()
                self._idem[key] = req
                self._retire_idem(req)
            requeued = 0
            for rec in ([] if datasets_only else state["pending"]):
                params = rec.get("params") or {}
                try:
                    self.submit(
                        str(rec["tenant"]), str(rec["discovery"]),
                        rec["test"],
                        modules=params.get("modules"),
                        n_perm=params.get("n_perm"),
                        seed=int(params.get("seed") or 0),
                        alternative=params.get("alternative", "greater"),
                        adaptive=bool(params.get("adaptive", False)),
                        deadline_s=params.get("deadline_s"),
                        idempotency_key=str(rec.get("key")),
                        # the journaled trace context: the re-queued run
                        # continues the CALLER's trace, so pre- and
                        # post-crash spans merge under one id (ISSUE 13)
                        trace_ctx=rec.get("trace"),
                    )
                    requeued += 1
                except ServeError as e:
                    # an unreplayable request (e.g. its dataset record is
                    # torn) must not resurrect on every boot: journal it
                    # terminally failed and move on
                    logger.warning("journal replay: request %s failed to "
                                   "re-queue: %s", rec.get("id"), e)
                    if self.journal is not None:
                        self.journal.append(
                            "failed", seq=rec.get("seq"), id=rec.get("id"),
                            key=rec.get("key"), error=f"replay: {e}",
                        )
        finally:
            self._replaying = False
            self._adopting = False
        summary = {
            "tenants": len(state["tenants"]),
            "datasets": len(state["datasets"]),
            "results": 0 if datasets_only else len(state["results"]),
            "failed": 0 if datasets_only else len(state["failed"]),
            "requeued": requeued,
        }
        if self.tel is not None:
            self.tel.emit(
                "journal_replayed", parent=self._serve_sid,
                adopted=not quiet, **summary,
            )
        return summary

    @staticmethod
    def _terminal_request(key: str, rec: dict, acc: dict) -> Request:
        """A done-shaped Request rebuilt from journal records (no plan —
        it never runs again; it only answers duplicate submissions)."""
        params = acc.get("params") or {}
        return Request(
            id=str(rec.get("id") or acc.get("id") or key),
            tenant=str(acc.get("tenant", "")),
            discovery=str(acc.get("discovery", "")),
            test=acc.get("test"),
            seed=int(params.get("seed") or 0),
            adaptive=bool(params.get("adaptive", False)),
            plan=None, pack_key=None, deadline=0.0, submitted_m=0.0,
            seq=int(acc.get("seq") or 0), journal_key=key,
        )

    # -- registration ------------------------------------------------------

    def register_tenant(self, name: str, weight: int = 1) -> None:
        with self._work:
            if name in self._tenants:
                self._tenants[name].weight = max(1, int(weight))
            else:
                self._tenants[name] = _Tenant(name, weight)
                self._tenant_order.append(name)
            self._rr = [
                n for n in self._tenant_order
                for _ in range(self._tenants[n].weight)
            ]
            self._rr_pos %= max(1, len(self._rr))
        if self.journal is not None and not self._replaying:
            self.journal.append("tenant", tenant=name,
                                weight=max(1, int(weight)))

    def register_dataset(self, tenant: str, name: str, *, network=None,
                         correlation=None, data=None, assignments=None,
                         beta=None) -> str:
        """Register one named dataset for ``tenant`` (creating the tenant
        at weight 1 if needed); returns the dataset's content digest —
        the identity the cross-request pack key is built from, so two
        tenants registering identical data can share dispatches.

        Two payload shapes (ISSUE 9): the dense one (``network`` +
        ``correlation`` [+ ``data``]) and the DATA-ONLY one (``data`` +
        ``beta`` — the soft-threshold derivation spec, no matrices),
        which serves atlas tenants whose n×n pair cannot exist. The
        data-only digest covers the derivation params (β, kind) beside
        the data content, so two derivations of the same data never
        share a pack or a warm pooled engine."""
        if tenant not in self._tenants:
            self.register_tenant(tenant)
        data_only = network is None and correlation is None
        if data_only:
            if beta is None or data is None:
                raise ServeError(
                    "a registration needs either network+correlation "
                    "(dense) or data+beta (data-only atlas payload)"
                )
            from ..ops.stats import normalize_net_beta

            beta = tuple(beta) if isinstance(beta, list) else beta
            b, kind = normalize_net_beta(beta)   # fail fast on a bad spec
            built = ds.build_data_only_datasets({name: data})
            dataset = built[name]
            digest = (
                f"{content_digest([dataset.data])}|beta:{b:g}|{kind}"
            )
        else:
            if beta is not None:
                raise ServeError(
                    "beta is the data-only derivation spec; a dense "
                    "registration (network+correlation) must not pass it"
                )
            built = ds.build_datasets(
                network={name: network},
                data=None if data is None else {name: data},
                correlation={name: correlation},
            )
            dataset = built[name]
            digest = content_digest(
                [dataset.correlation, dataset.network, dataset.data]
            )
        norm = None
        if assignments is not None:
            norm = ds.normalize_module_assignments(
                assignments, built, [name]
            )[name]
        with self._work:
            self._tenants[tenant].datasets[name] = _Dataset(
                name, dataset, norm, digest,
                beta=beta if data_only else None,
            )
        if (self.journal is not None and not self._replaying
                and not self._fixture_depth):
            # the durable dataset reference recovery re-registers from:
            # inline payloads journal their (encoded) matrices — the same
            # bytes the wire carried in — so `serve --recover` needs no
            # client re-upload (fixtures journal parameters instead, via
            # register_fixture)
            from .protocol import encode_arrays

            self.journal.append(
                "dataset", tenant=tenant, name=name, form="inline",
                digest=digest,
                payload=encode_arrays(dict(
                    network=network, correlation=correlation, data=data,
                    assignments=assignments,
                    beta=list(beta) if isinstance(beta, tuple) else beta,
                )),
            )
        return digest

    def register_fixture(self, tenant: str, prefix: str = "fx", *,
                         genes: int = 120, modules: int = 3,
                         n_samples: int = 16, seed: int = 7) -> dict:
        """Generate and register a deterministic mixed discovery/test pair
        (:func:`netrep_tpu.data.make_mixed_pair`) — the daemon drill and
        load generator register fixtures by PARAMETERS instead of
        shipping matrices over the wire."""
        from ..data import make_mixed_pair

        mixed = make_mixed_pair(genes, modules, n_samples=n_samples,
                                seed=seed)
        (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
        assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
        for lab, idx in mixed["specs"]:
            for i in idx:
                assign[f"node_{i}"] = str(lab)
        d_name, t_name = f"{prefix}_d", f"{prefix}_t"
        # journal the fixture by PARAMETERS (re-derivable, cheap) rather
        # than as two inline matrix payloads
        if self.journal is not None and not self._replaying:
            self.journal.append(
                "dataset", tenant=tenant, name=prefix, form="fixture",
                payload=dict(prefix=prefix, genes=int(genes),
                             modules=int(modules), n_samples=int(n_samples),
                             seed=int(seed)),
            )
        self._fixture_depth += 1
        try:
            self.register_dataset(tenant, d_name, network=dn,
                                  correlation=dc, data=dd,
                                  assignments=assign)
            self.register_dataset(tenant, t_name, network=tn,
                                  correlation=tc, data=td)
        finally:
            self._fixture_depth -= 1
        return {"discovery": d_name, "test": t_name,
                "labels": [str(lab) for lab, _ in mixed["specs"]]}

    # -- submission --------------------------------------------------------

    def _dataset(self, tenant: str, name: str) -> _Dataset:
        ten = self._tenants.get(tenant)
        if ten is None:
            raise ServeError(f"unknown tenant {tenant!r}")
        d = ten.datasets.get(name)
        if d is None:
            raise ServeError(
                f"tenant {tenant!r} has no dataset {name!r}; register it "
                "first"
            )
        return d

    def _auto_n_perm(self, labels, with_data: bool) -> int:
        # the library's Bonferroni auto rule (models/preservation.py) —
        # mirrored so a served request defaults exactly like a direct call
        n_stats_eff = 7 if with_data else 3
        return max(1000, pv.required_perms(
            0.05, n_tests=len(labels) * n_stats_eff
        ))

    def _build_plan(self, disc: _Dataset, test: _Dataset, modules,
                    n_perm, seed, alternative, adaptive, rule) -> RequestPlan:
        if disc.assignments is None:
            raise ServeError(
                f"dataset {disc.name!r} was registered without module "
                "assignments and cannot serve as a discovery dataset"
            )
        labels, mod_specs, counts, pool = _overlap_setup(
            disc.ds, test.ds, disc.assignments, modules,
            self.config.background_label, self.config.null,
        )
        with_data = disc.ds.data is not None and test.ds.data is not None
        np_this = (
            int(n_perm) if n_perm is not None
            else self.config.default_n_perm
            or self._auto_n_perm(labels, with_data)
        )
        return RequestPlan(
            labels=labels, specs=mod_specs, counts=counts, pool=pool,
            n_perm=np_this, seed=int(seed), alternative=alternative,
            adaptive=bool(adaptive), rule=rule,
        )

    def _dedup_locked(self, key: str | None) -> Request | None:
        """Idempotency lookup (caller holds the lock): a seen key returns
        the original request — attaching to it while in flight, answering
        from its stored/journaled result after completion — instead of
        ever recomputing (the contract that makes client
        retry-with-backoff safe by construction)."""
        if key is None:
            return None
        req = self._idem.get(key)
        if req is None:
            return None
        state = "completed" if req.done.is_set() else "inflight"
        ten = self._tenants.get(req.tenant)
        if ten is not None:
            ten.counters["deduped"] += 1
        if self.tel is not None:
            self.tel.emit("request_deduped", tenant=req.tenant, key=key,
                          state=state, parent=req.sid)
        return req

    # -- overload / brownout (ISSUE 10) ------------------------------------

    def _req_nperm(self, req: Request) -> int:
        p = req.plan
        return int(p.plan.n_perm if isinstance(p, _MultiPlan) else p.n_perm)

    def _rate_pps(self) -> float | None:
        """Steady-state serving throughput estimate (perms/s): configured
        assumption, else the server's own measured rate, else the perf
        ledger's serve/run history (read once, cached) — None when
        nothing is known (brownout then stays off: no guessing). The
        roofline note (ISSUE 18) deliberately does NOT feed this chain:
        it is process-wide, so in a multi-server process (fleet tests,
        embedded use) an unrelated engine run's rate would masquerade as
        THIS server's serving rate and corrupt the drain estimate — the
        note stays a display gauge (``stats()`` utilisation)."""
        if self.config.brownout_rate_pps:
            return float(self.config.brownout_rate_pps)
        if self._busy_s > 0 and self._served_perms > 0:
            return self._served_perms / self._busy_s
        if not hasattr(self, "_ledger_rate"):
            self._ledger_rate = None
            try:
                from ..utils import perfledger

                path = perfledger.default_path()
                entries = [
                    float(e["perms_per_sec"])
                    for e in perfledger.read_entries(path)
                    if e.get("source") in ("serve", "run")
                ][-8:]
                if entries:
                    self._ledger_rate = sorted(entries)[len(entries) // 2]
            except OSError:
                pass
        return self._ledger_rate

    @staticmethod
    def _roofline_note() -> dict | None:
        """The most recent engine run's roofline block (PEEK semantics —
        `stats()` is polled, so the note must stay readable; bench rows
        are the consuming reader)."""
        from ..utils import costmodel

        return costmodel.last_run_note(consume=False)

    def _drain_estimate_locked(self, extra_perms: int = 0) -> float | None:
        rate = self._rate_pps()
        if not rate or rate <= 0:
            return None
        backlog = extra_perms + sum(
            self._req_nperm(r)
            for t in self._tenants.values() for r in t.pending
        )
        return backlog / rate

    def _update_brownout_locked(self, est: float | None) -> bool:
        """Hysteresis state machine around the backlog drain estimate:
        enter past ``brownout_enter_s``, exit below ``brownout_exit_s``
        (default half of enter), one telemetry event per transition."""
        cfg = self.config
        if cfg.brownout_enter_s is None or est is None:
            return self._brownout
        exit_s = (cfg.brownout_exit_s if cfg.brownout_exit_s is not None
                  else cfg.brownout_enter_s / 2.0)
        depth = sum(len(t.pending) for t in self._tenants.values())
        if not self._brownout and est > cfg.brownout_enter_s:
            self._brownout = True
            if self.tel is not None:
                self.tel.emit("serve_brownout_enter",
                              est_drain_s=float(est), queue_depth=depth,
                              parent=self._serve_sid)
        elif self._brownout and est < exit_s:
            self._brownout = False
            if self.tel is not None:
                self.tel.emit("serve_brownout_exit",
                              est_drain_s=float(est), queue_depth=depth,
                              parent=self._serve_sid)
        return self._brownout

    def submit(self, tenant: str, discovery: str, test,
               modules: Sequence | None = None, n_perm: int | None = None,
               seed: int = 0, alternative: str = "greater",
               adaptive: bool = False, rule=None,
               deadline_s: float | None = None,
               idempotency_key: str | None = None,
               trace_ctx: dict | None = None) -> Request:
        """Validate, admit, and enqueue one analyze request; returns the
        request handle (``wait`` for the result). ``test`` may be a list
        of test-dataset names sharing a node universe — the request then
        rides the MultiTestEngine T-axis and returns per-test results.

        ``idempotency_key`` (ISSUE 10): a client-chosen durable identity.
        A duplicate submission with a seen key never recomputes — it
        attaches to the in-flight request or returns the completed
        (journaled) result. With a journal attached, the ``accepted``
        record is fsynced before this method returns.

        ``trace_ctx`` (ISSUE 13): the caller's W3C-style trace context
        (``{"trace": <hex id>, "parent": <span id|None>}`` — the clients
        mint one per logical request). It is journaled with the
        ``accepted`` record (so ``--recover`` resumes the SAME trace) and
        stamped on the request's telemetry span; a malformed context is
        replaced by a server-minted one, never an error."""
        if alternative not in ("greater", "less", "two.sided"):
            raise ServeError(f"bad alternative {alternative!r}")
        from .protocol import mint_trace_ctx, normalize_trace_ctx

        tctx = normalize_trace_ctx(trace_ctx) or mint_trace_ctx()
        with self._work:
            dup = self._dedup_locked(idempotency_key)
            if dup is not None:
                return dup
        disc = self._dataset(tenant, discovery)
        multi = isinstance(test, (list, tuple))
        if multi and len(test) == 1:
            test, multi = test[0], False
        if multi:
            tests = [self._dataset(tenant, t) for t in test]
            if disc.beta is not None or any(
                t.beta is not None for t in tests
            ):
                raise ServeError(
                    "multi-test requests need materialized matrices (the "
                    "vmap_tests contract stacks the T cohorts); data-only "
                    "datasets are served pairwise"
                )
            names0 = tests[0].ds.node_names
            if any(t.ds.node_names != names0 for t in tests[1:]):
                raise ServeError(
                    "multi-test requests need test datasets with an "
                    "identical node universe (the vmap_tests contract)"
                )
            if len({t.ds.data is not None for t in tests}) != 1:
                raise ServeError(
                    "multi-test requests need test datasets agreeing on "
                    "data presence"
                )
            plan = _MultiPlan(
                plan=self._build_plan(disc, tests[0], modules, n_perm,
                                      seed, alternative, adaptive, rule),
                test_names=[t.name for t in tests],
            )
            pack_key = None   # a multi-test request is its own pack
        else:
            tds = self._dataset(tenant, test)
            if (disc.beta is None) != (tds.beta is None):
                raise ServeError(
                    "cannot mix a data-only dataset with a dense one in "
                    "one request: both sides must carry matrices, or both "
                    "data+beta"
                )
            if disc.beta is not None and disc.beta != tds.beta:
                raise ServeError(
                    f"discovery and test were registered with different "
                    f"derivation specs ({disc.beta!r} vs {tds.beta!r}); "
                    "re-register one side"
                )
            plan = self._build_plan(disc, tds, modules, n_perm, seed,
                                    alternative, adaptive, rule)
            # compatibility identity: same matrices + same engine config
            # => same pool, same kernels, one shared dispatch stream
            if (self.config.cross_pair_packing and disc.beta is None
                    and tds.beta is None):
                # cross-pair key (ISSUE 17): the GRID identity — shared
                # test matrices + byte-equal permutation pool + agreeing
                # data presence. Discovery matrices drop out of the key
                # because GridPackedEngine substitutes each request's own
                # per-bucket discovery props (data-only pairs keep the
                # pairwise key: their kernel closes over the data columns)
                pool_sig = hashlib.blake2b(
                    np.ascontiguousarray(plan.pool, dtype=np.int64),
                    digest_size=8,
                ).hexdigest()
                pack_key = ("xpair", tds.digest, pool_sig,
                            disc.ds.data is not None, self.config.null,
                            self._engine_cfg_id)
            else:
                pack_key = (disc.digest, tds.digest, self.config.null,
                            self._engine_cfg_id)
        now = time.monotonic()
        with self._work:
            # authoritative dedup under the lock (a concurrent duplicate
            # may have landed while the plan was being built)
            dup = self._dedup_locked(idempotency_key)
            if dup is not None:
                return dup
            ten = self._tenants[tenant]
            if not self._accepting:
                ten.counters["rejected"] += 1
                if self.tel is not None:
                    self.tel.emit("request_rejected", tenant=tenant,
                                  reason="draining")
                raise ServeError("server is draining; not accepting work")
            plan_np = int(plan.plan.n_perm if multi else plan.n_perm)
            est = self._drain_estimate_locked(extra_perms=plan_np)
            brown = self._update_brownout_locked(est)
            retry_after = round(est, 3) if est is not None else None
            if brown and not (self._replaying or self._adopting):
                # predictable shedding: the NEWEST request of the
                # lowest-weight tenants is refused first, with a drain-
                # time hint — heavier tenants keep their priority
                min_w = min(t.weight for t in self._tenants.values())
                if ten.weight <= min_w:
                    ten.counters["rejected"] += 1
                    if self.tel is not None:
                        self.tel.emit(
                            "request_rejected", tenant=tenant,
                            reason="brownout",
                            queue_depth=len(ten.pending),
                            retry_after_s=retry_after,
                        )
                    raise QueueFull(
                        f"service is browned out (estimated backlog "
                        f"drain {est:.1f}s); retry later",
                        retry_after_s=retry_after,
                    )
            if (len(ten.pending) >= self.config.max_queue
                    and not (self._replaying or self._adopting)):
                # (replayed requests were admitted once — the journal's
                # accepted records re-queue past the bound by design)
                ten.counters["rejected"] += 1
                if self.tel is not None:
                    self.tel.emit(
                        "request_rejected", tenant=tenant,
                        reason="queue_full",
                        queue_depth=len(ten.pending),
                        retry_after_s=retry_after,
                    )
                raise QueueFull(
                    f"tenant {tenant!r} queue is full "
                    f"({self.config.max_queue}); retry later",
                    retry_after_s=retry_after,
                )
            if (brown and self.config.brownout_nperm_cap is not None
                    and not (self._replaying or self._adopting)):
                # opt-in graceful degradation: browned-out admissions run
                # at a capped budget (documented to change results)
                cap = int(self.config.brownout_nperm_cap)
                if multi:
                    plan.plan.n_perm = min(plan.plan.n_perm, cap)
                else:
                    plan.n_perm = min(plan.n_perm, cap)
            self._seq += 1
            jkey = idempotency_key or f"auto-{uuid.uuid4().hex[:12]}"
            if self.journal is not None and not self._replaying:
                # the write-ahead promise, fsynced BEFORE admission: once
                # submit returns, a SIGKILL cannot lose this request
                self.journal.append(
                    "accepted", seq=self._seq, id=f"r{self._seq}",
                    key=jkey, tenant=tenant, discovery=discovery,
                    test=list(test) if multi else test,
                    trace=dict(tctx),
                    digests=(
                        [self._dataset(tenant, discovery).digest]
                        + [self._dataset(tenant, t).digest
                           for t in (test if multi else [test])]
                    ),
                    params=dict(
                        modules=(list(modules) if modules is not None
                                 else None),
                        n_perm=(int(n_perm) if n_perm is not None
                                else None),
                        seed=int(seed), alternative=alternative,
                        adaptive=bool(adaptive),
                        deadline_s=(float(deadline_s)
                                    if deadline_s is not None else None),
                    ),
                )
            req = Request(
                id=f"r{self._seq}", tenant=tenant, discovery=discovery,
                test=list(test) if multi else test, seed=int(seed),
                adaptive=bool(adaptive), plan=plan, pack_key=pack_key,
                deadline=now + (
                    deadline_s if deadline_s is not None
                    else self.config.slo_s
                ),
                submitted_m=now, seq=self._seq, journal_key=jkey,
                trace=tctx["trace"], trace_parent=tctx["parent"],
            )
            self._idem[jkey] = req
            ten.counters["received"] += 1
            self._last_active_m = now
            if self.tel is not None:
                req.sid = self.tel.new_span_id()
                self.tel.emit(
                    "request_received", span=req.sid,
                    parent=self._serve_sid, tenant=tenant,
                    discovery=discovery,
                    test="+".join(req.test) if multi else test,
                    n_perm=int(
                        plan.plan.n_perm if multi else plan.n_perm
                    ),
                    seed=int(seed), adaptive=bool(adaptive),
                    queue_depth=len(ten.pending) + 1,
                    # trace-ctx stamp (ISSUE 13): build_span_tree
                    # propagates `trace` down the request's whole
                    # subtree, across processes and restarts
                    trace=req.trace,
                    **({"trace_parent": req.trace_parent}
                       if req.trace_parent else {}),
                )
            ten.pending.append(req)
            self._work.notify_all()
        return req

    def wait(self, req: Request, timeout: float | None = None) -> dict:
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {req.id} did not finish in time")
        if req.error is not None:
            raise ServeError(f"request {req.id}: {req.error}")
        return req.result

    def analyze(self, tenant: str, discovery: str, test, *,
                timeout: float | None = None, **kw) -> dict:
        """Blocking submit + wait (the one-call client surface)."""
        return self.wait(
            self.submit(tenant, discovery, test, **kw), timeout=timeout
        )

    # -- scheduling --------------------------------------------------------

    def _any_pending_locked(self) -> bool:
        return any(t.pending for t in self._tenants.values())

    def _take_pack_locked(self) -> list[Request] | None:
        """Pick the next batch under the lock: WRR across tenants picks
        the seed tenant, oldest-deadline-first picks its seed request, and
        the seed's pack key pulls compatible requests from every tenant's
        queue (seed tenant first) up to ``max_pack``."""
        if not self._rr or not self._any_pending_locked():
            return None
        n = len(self._rr)
        ten = None
        for step in range(n):
            cand = self._tenants[self._rr[(self._rr_pos + step) % n]]
            if cand.pending:
                ten = cand
                self._rr_pos = (self._rr_pos + step + 1) % n
                break
        if ten is None:
            return None
        seed_req = min(ten.pending, key=lambda r: (r.deadline, r.seq))
        ten.pending.remove(seed_req)
        batch = [seed_req]
        self._fill_pack_locked(batch, ten.name)
        return batch

    def _fill_pack_locked(self, batch: list[Request],
                          seed_tenant: str) -> None:
        """Pull compatible requests from every tenant's queue (seed tenant
        first) into ``batch``, up to ``max_pack``."""
        seed_req = batch[0]
        if (seed_req.pack_key is None or seed_req.solo_only
                or self.config.max_pack <= 1):
            return
        order = [seed_tenant] + [
            t for t in self._tenant_order if t != seed_tenant
        ]
        for name in order:
            if len(batch) >= self.config.max_pack:
                break
            t = self._tenants[name]
            matches = sorted(
                (r for r in t.pending
                 if r.pack_key == seed_req.pack_key and not r.solo_only),
                key=lambda r: (r.deadline, r.seq),
            )
            for r in matches:
                if len(batch) >= self.config.max_pack:
                    break
                t.pending.remove(r)
                batch.append(r)

    def _trim_pack_locked(self, batch: list[Request]) -> None:
        """Canonicalize the pack size to the largest power of two that
        fits, re-queueing the tail (original deadlines kept — they seed
        the very next pack). Arbitrary sizes would mint a fresh engine
        structure per composition (the warm pool keys on it); powers of
        two bound the composition space to log(max_pack) shapes per base
        signature, so steady-state traffic converges onto warm compiled
        programs instead of compiling every batch-size it happens to
        draw."""
        if len(batch) < 2:
            return
        size = 1
        while size * 2 <= len(batch):
            size *= 2
        for r in batch[size:]:
            self._tenants[r.tenant].pending.append(r)
        del batch[size:]

    def _loop(self) -> None:
        while True:
            with self._work:
                batch = self._take_pack_locked()
                while batch is None and not self._stop:
                    self._work.wait(0.25)
                    batch = self._take_pack_locked()
                if batch is None:
                    return
                if (self.config.pack_window_s > 0
                        and len(batch) < self.config.max_pack
                        and batch[0].pack_key is not None
                        and not batch[0].solo_only and not self._stop):
                    # batching window: let concurrent arrivals coalesce
                    # into the shared dispatch before it launches —
                    # milliseconds of queue wait against a service time
                    # of seconds, and the difference between N singleton
                    # compiles and one shared pack
                    self._work.wait(self.config.pack_window_s)
                    self._fill_pack_locked(batch, batch[0].tenant)
                self._trim_pack_locked(batch)
                self._inflight = len(batch)
            try:
                self._execute(batch)
            except SimulatedCrash:
                # the in-process SIGKILL stand-in (crash drills): the
                # worker dies HERE exactly as the process would — waiters
                # stay blocked, queued work stays queued; only the
                # journal's accepted records and the pack checkpoints
                # survive, for the next `--recover` boot to pick up
                return
            # netrep: allow(exception-taxonomy) — the worker outlives any batch failure; the error is logged and delivered to every waiter below
            except Exception:   # defensive: the worker must never die
                logger.warning(
                    "serve worker: unhandled batch failure", exc_info=True
                )
                for r in batch:
                    if not r.done.is_set():
                        r.error = r.error or "internal server error"
                        r.done.set()
            finally:
                with self._work:
                    self._inflight = 0
                    self._work.notify_all()

    # -- execution ---------------------------------------------------------

    def _finish(self, req: Request, result: dict | None, error: str | None,
                pack_id: str, pack_size: int, pool_hit: bool) -> None:
        ten = self._tenants[req.tenant]
        now = time.monotonic()
        if error is None:
            req.result = dict(
                result,
                request_id=req.id, tenant=req.tenant,
                discovery=req.discovery, test=req.test,
                trace=req.trace,
                latency_s=now - req.submitted_m,
                pack_id=pack_id, pack_size=pack_size, pool_hit=pool_hit,
            )
            ten.counters["done"] += 1
            latency = now - req.submitted_m
            with self._work:
                self._last_active_m = now
                ten.lat_hist.observe(latency)
                self._slo_mark_locked(ten, now, latency > self.config.slo_s)
            self._account_cost(req, result.get("cost"))
        else:
            req.error = error
            ten.counters["failed"] += 1
            with self._work:
                self._last_active_m = now
                self._slo_mark_locked(ten, now, True)
        if self.journal is not None and req.journal_key is not None:
            # terminal journal record: done carries the full encoded
            # result (what a post-restart duplicate is answered with) +
            # its digest; failed carries the error — neither re-queues
            # on the next --recover boot
            from .protocol import encode_arrays

            if error is None:
                enc = encode_arrays(req.result)
                self.journal.append(
                    "done", seq=req.seq, id=req.id, key=req.journal_key,
                    tenant=req.tenant, digest=jnl.result_digest(enc),
                    result=enc,
                )
            else:
                self.journal.append(
                    "failed", seq=req.seq, id=req.id, key=req.journal_key,
                    tenant=req.tenant, error=error,
                )
        if self.tel is not None:
            data = dict(
                tenant=req.tenant, s=now - req.submitted_m,
                pack=pack_id, pack_size=pack_size, ok=error is None,
            )
            if error is None:
                data["perms"] = int(result.get("completed", 0))
            else:
                data["error"] = error
            self.tel.emit("request_done", span=req.sid, **data)
        self._retire_idem(req)
        req.done.set()

    def _slo_mark_locked(self, ten: _Tenant, now: float,
                         missed: bool) -> None:
        """Record one terminal request in the tenant's SLO sliding window
        (caller holds the lock) and trim marks older than the window."""
        ten.slo_marks.append((now, bool(missed)))
        horizon = now - self.config.slo_window_s
        while ten.slo_marks and ten.slo_marks[0][0] < horizon:
            ten.slo_marks.pop(0)
        # slo_burn anomaly (ISSUE 20), latched per excursion: the first
        # mark that pushes the tenant past its error budget fires the
        # pinned detector; recovery below budget re-arms it. Same
        # emit-under-lock precedent as the brownout transition events.
        burn = self._burn_rate_locked(ten, now)
        if missed and burn > 1.0 and not ten.burn_flagged:
            ten.burn_flagged = True
            from ..utils import detectors

            detectors.fire("slo_burn", telemetry=self.tel,
                           tenant=ten.name, burn_rate=round(burn, 4),
                           window_s=self.config.slo_window_s,
                           budget=self.config.slo_budget)
        elif burn <= 1.0:
            ten.burn_flagged = False

    def _burn_rate_locked(self, ten: _Tenant, now: float) -> float:
        """SLO burn rate: miss fraction over the sliding window divided
        by the error budget (1.0 = consuming the budget exactly at the
        sustainable rate; 0 with no terminal requests in the window)."""
        horizon = now - self.config.slo_window_s
        marks = [m for t, m in ten.slo_marks if t >= horizon]
        if not marks:
            return 0.0
        frac = sum(marks) / len(marks)
        return frac / max(self.config.slo_budget, 1e-9)

    def _account_cost(self, req: Request, cost: dict | None) -> None:
        """Fold one request's attributed cost (ISSUE 13) into its
        tenant's rollups and emit the pinned ``request_cost`` event under
        the request's span — the per-tenant device-time signal `top`,
        ``metrics_text()``, and fleet admission read."""
        if cost is None:
            return
        ten = self._tenants[req.tenant]
        with self._work:
            for k in ("device_s", "transfer_s", "compile_s_amortized"):
                ten.cost[k] += float(cost.get(k, 0.0))
            for k in ("perms", "bytes_to_host"):
                ten.cost[k] += int(cost.get(k, 0))
            ten.cost_hist.observe(float(cost.get("device_s", 0.0)))
        if self.tel is not None:
            self.tel.emit(
                "request_cost", parent=req.sid, tenant=req.tenant,
                trace=req.trace, pack_weight=int(cost.get("weight", 0)),
                device_s=float(cost.get("device_s", 0.0)),
                transfer_s=float(cost.get("transfer_s", 0.0)),
                perms=int(cost.get("perms", 0)),
                bytes_to_host=int(cost.get("bytes_to_host", 0)),
                compile_s_amortized=float(
                    cost.get("compile_s_amortized", 0.0)
                ),
            )

    def _retire_idem(self, req: Request) -> None:
        """Bound the idempotency map: terminal requests stay answerable
        up to ``idem_cache`` of them; beyond that the oldest evict (a
        duplicate of an evicted key recomputes to the same result)."""
        if req.journal_key is None:
            return
        with self._work:
            self._idem_done.append(req.journal_key)
            while len(self._idem_done) > self.config.idem_cache:
                old = self._idem_done.pop(0)
                stale = self._idem.get(old)
                if stale is not None and stale.done.is_set():
                    del self._idem[old]

    def _expire(self, req: Request, miss_s: float, folded: int,
                cost: dict | None = None) -> None:
        """Cancel a deadline-missed request (ISSUE 10): the ``expired``
        counter, a terminal ``failed`` journal record (a deadline miss
        must not resurrect on ``--recover``), the pinned
        ``request_expired`` event with the miss, and the waiter's error.
        ``cost`` (ISSUE 13) is the share of the pack the request consumed
        before cancellation — attributed like any other, so the tenant's
        device-time rollup never under-counts abandoned work."""
        ten = self._tenants[req.tenant]
        ten.counters["expired"] += 1
        with self._work:
            self._slo_mark_locked(ten, time.monotonic(), True)
        self._account_cost(req, cost)
        error = (f"deadline exceeded by {miss_s:.2f}s "
                 f"(cancelled after {int(folded)} permutations)")
        req.error = error
        if self.journal is not None and req.journal_key is not None:
            self.journal.append(
                "failed", seq=req.seq, id=req.id, key=req.journal_key,
                tenant=req.tenant, error=error,
            )
        if self.tel is not None:
            self.tel.emit(
                "request_expired", span=req.sid, tenant=req.tenant,
                miss_s=float(miss_s), folded=int(folded),
                s=time.monotonic() - req.submitted_m,
            )
        self._retire_idem(req)
        req.done.set()

    def _account_pack_locked(self, wall_s: float, perms: int) -> None:
        """Fold one pack's measured throughput into the brownout rate
        estimate and re-evaluate the brownout state (the exit path: the
        queue just got shorter)."""
        with self._work:
            self._busy_s += float(wall_s)
            self._served_perms += int(perms)
            self._update_brownout_locked(self._drain_estimate_locked())

    def _pack_ckpt_path(self, batch: list[Request], plans) -> str | None:
        """Deterministic per-pack checkpoint path (None when
        checkpointing is off): keyed on the members' durable identities,
        so the same requests re-queued by ``--recover`` resume the same
        file — any other composition recomputes, bit-identically."""
        if self._ckpt_dir is None:
            return None
        if any(r.journal_key is None for r in batch):
            return None
        os.makedirs(self._ckpt_dir, exist_ok=True)
        return jnl.pack_checkpoint_path(
            self._ckpt_dir, self._engine_cfg_id,
            [(r.journal_key, p.seed, p.n_perm, p.signature())
             for r, p in zip(batch, plans)],
        )

    def _requeue_solo(self, batch: list[Request]) -> None:
        """A failed pack is split: every member re-queues solo-only (front
        of its tenant's queue, original deadline), so one poisoned
        request — or a device fault mid-pack — fails alone on its retry
        instead of taking its pack-mates down."""
        with self._work:
            for r in batch:
                r.solo_only = True
                self._tenants[r.tenant].pending.append(r)
            self._work.notify_all()
        if self.tel is not None:
            for r in batch:
                self.tel.emit("request_requeued", tenant=r.tenant,
                              reason="pack_failed", parent=r.sid)

    def _execute(self, batch: list[Request]) -> None:
        if self.config.enforce_deadlines:
            # already-expired requests are cancelled before any dispatch
            # (the queue-side deadline check; mid-pack expiry is the
            # monitor's chunk-boundary sweep)
            now = time.monotonic()
            live = []
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    self._expire(r, now - r.deadline, folded=0)
                else:
                    live.append(r)
            batch = live
            if not batch:
                return
        # under the condition: `packs` in stats() reads this counter from
        # client threads (ISSUE 12 thread-shared-state discipline)
        with self._work:
            self._pack_seq += 1
            pack_id = f"p{self._pack_seq}"
        multi = isinstance(batch[0].plan, _MultiPlan)
        # canonical member order → stable pool signatures across packs
        if not multi:
            batch = sorted(batch, key=lambda r: (r.plan.signature(), r.seq))
        tel_cm = self.tel.activate() if self.tel is not None else None
        if tel_cm is not None:
            tel_cm.__enter__()
        try:
            if multi:
                self._execute_multi(batch[0], pack_id)
            else:
                self._execute_pack(batch, pack_id)
        # netrep: allow(exception-taxonomy) — serving fault boundary: the error becomes each waiter's error result (packs retry solo first); crashes (BaseException) still unwind
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            if len(batch) > 1:
                self._requeue_solo(batch)
            else:
                self._finish(batch[0], None, err, pack_id, len(batch),
                             False)
        finally:
            if tel_cm is not None:
                tel_cm.__exit__(None, None, None)

    def _maybe_record_coldstart(self, results, wall_s: float,
                                perms_done: int) -> None:
        """Fleet cold-start baseline (ISSUE 14 satellite): a fleet
        replica's FIRST completed pack records its compile span to the
        perf ledger under a fleet-labeled fingerprint — the measured
        number the still-open AOT warm-start goal (ROADMAP item 1) has
        to beat (``compile_s → ~0 on first request`` is its pinned
        proof). Env-gated like every ledger writer; stand-alone servers
        (no ``fleet_label``) record nothing."""
        if not self.config.fleet_label or self._coldstart_done:
            return
        self._coldstart_done = True
        if not os.environ.get("NETREP_PERF_LEDGER"):
            return
        compile_s = None
        for res in results:
            cost = res.get("cost")
            if cost and cost.get("pack_totals"):
                compile_s = float(
                    cost["pack_totals"].get("compile_s_amortized", 0.0)
                )
                break
        import jax

        from ..utils import perfledger

        backend = jax.default_backend()
        label = self.config.fleet_label
        perfledger.append_entry(perfledger.make_entry(
            f"serve-fleet-coldstart|{label}|{backend}",
            perms_done / wall_s if wall_s > 0 else 0.0,
            "serve", backend=backend, mode="fleet-coldstart",
            compile_s=compile_s, n_perm=perms_done,
            metric=f"serve-fleet coldstart {label}",
        ))

    def _pool_key(self, kind: str, digests: tuple, plans) -> tuple:
        return (kind, digests, self._engine_cfg_id,
                tuple(p.signature() for p in plans))

    def _emit_pool(self, hit: bool, pack_id: str, n: int) -> None:
        if self.tel is not None:
            self.tel.emit(
                "serve_pool_hit" if hit else "serve_pool_miss",
                pack=pack_id, n_requests=n, **self.pool.stats(),
            )

    def _pack_engine(self, disc: _Dataset, test: _Dataset, plans):
        """Build the packed engine for one (discovery, test) pair — the
        warm-pool builder shared by pack execution and the boot-time AOT
        preload (ISSUE 15), so preloaded engines are EXACTLY the ones the
        first request would build."""
        cfg = self.config.engine
        if disc.beta is not None:
            # data-only atlas pack (ISSUE 9): the engine derives every
            # submatrix from data columns with the registered spec
            cfg = dataclasses.replace(
                cfg, network_from_correlation=disc.beta
            )
        return PackedEngine(
            disc.ds.correlation, disc.ds.network, disc.ds.data,
            test.ds.correlation, test.ds.network, test.ds.data,
            [p.specs for p in plans], plans[0].pool,
            config=cfg,
        )

    def _grid_pack_engine(self, discs, test: _Dataset, plans):
        """Cross-pair pack builder (ISSUE 17): one discovery source per
        request, shared test matrices — the grid-column engine. Only
        dense members reach here (the cross-pair key excludes beta
        registrations)."""
        return GridPackedEngine(
            [(d.ds.correlation, d.ds.network, d.ds.data) for d in discs],
            test.ds.correlation, test.ds.network, test.ds.data,
            [p.specs for p in plans], plans[0].pool,
            config=self.config.engine,
        )

    def _execute_pack(self, batch: list[Request], pack_id: str) -> None:
        plans = [r.plan for r in batch]
        assign_bases(plans)
        discs = [self._dataset(r.tenant, r.discovery) for r in batch]
        disc = discs[0]
        test = self._dataset(batch[0].tenant, batch[0].test)
        if any(d.digest != disc.digest for d in discs[1:]):
            # cross-pair pack (ISSUE 17): members share the test dataset
            # and pool but carry per-request discovery matrices
            key = self._pool_key(
                "gridpacked",
                (tuple(d.digest for d in discs), test.digest), plans,
            )
            engine, hit = self.pool.get(
                key, lambda: self._grid_pack_engine(discs, test, plans)
            )
        else:
            key = self._pool_key("packed", (disc.digest, test.digest),
                                 plans)
            engine, hit = self.pool.get(
                key, lambda: self._pack_engine(disc, test, plans)
            )
        self._emit_pool(hit, pack_id, len(batch))
        if self.tel is not None:
            for r in batch:
                self.tel.emit(
                    "request_packed", parent=r.sid, tenant=r.tenant,
                    pack=pack_id, n_requests=len(batch), pool_hit=hit,
                    queued_s=time.monotonic() - r.submitted_m,
                )
        for r, p in zip(batch, plans):
            p.deadline = (r.deadline if self.config.enforce_deadlines
                          else None)
        ckpt_path = self._pack_ckpt_path(batch, plans)
        kw = dict(
            telemetry=self.tel, fault_policy=self._fault,
            checkpoint_path=ckpt_path,
            checkpoint_every=self.config.checkpoint_every,
        )
        t0 = time.perf_counter()
        try:
            # export-on-miss scope (ISSUE 15): programs this pack had to
            # jit-compile are serialized for the next boot / fleet peer
            with self._aot_export_scope():
                if self.tel is not None:
                    with self.tel.span("pack", pack=pack_id,
                                       n_requests=len(batch),
                                       tenants=sorted({r.tenant
                                                       for r in batch})):
                        results = run_pack(engine, plans, **kw)
                else:
                    results = run_pack(engine, plans, **kw)
        except BaseException:
            # a failed run may leave the engine's device state suspect —
            # drop it from the warm pool before the error propagates
            # (the pack checkpoint, if any, stays for the solo retries).
            # BaseException, not Exception: a KeyboardInterrupt or
            # SimulatedCrash-class unwind mid-pack leaves the engine just
            # as suspect, and `raise` re-raises it unchanged (ISSUE 12)
            self.pool.discard(key)
            raise
        if ckpt_path is not None:
            # the pack completed: its checkpoint is spent (leaving it
            # would only grow the directory; a re-run recomputes exactly)
            try:
                os.unlink(ckpt_path)
            except OSError:
                pass
        wall_s = time.perf_counter() - t0
        perms_done = sum(int(res.get("completed", 0)) for res in results
                         if not res.get("expired"))
        self._account_pack_locked(wall_s, perms_done)
        self._maybe_record_coldstart(results, wall_s, perms_done)
        for r, res in zip(batch, results):
            if res.get("expired"):
                self._expire(r, res["deadline_miss_s"],
                             res.get("completed", 0),
                             cost=res.get("cost"))
            else:
                self._finish(r, res, None, pack_id, len(batch), hit)

    def _execute_multi(self, req: Request, pack_id: str) -> None:
        from ..parallel.multitest import MultiTestEngine

        mp: _MultiPlan = req.plan
        plan = mp.plan
        plan.base = 0
        disc = self._dataset(req.tenant, req.discovery)
        tests = [self._dataset(req.tenant, t) for t in mp.test_names]
        key = self._pool_key(
            "multi", (disc.digest,) + tuple(t.digest for t in tests),
            [plan],
        )

        def build():
            with_data = (disc.ds.data is not None
                         and tests[0].ds.data is not None)
            return MultiTestEngine(
                disc.ds.correlation, disc.ds.network, disc.ds.data,
                np.stack([t.ds.correlation for t in tests]),
                np.stack([t.ds.network for t in tests]),
                [t.ds.data for t in tests] if with_data else None,
                plan.specs, plan.pool, config=self.config.engine,
            )

        engine, hit = self.pool.get(key, build)
        self._emit_pool(hit, pack_id, 1)
        if self.tel is not None:
            self.tel.emit(
                "request_packed", parent=req.sid, tenant=req.tenant,
                pack=pack_id, n_requests=1, pool_hit=hit, taxis=len(tests),
                queued_s=time.monotonic() - req.submitted_m,
            )
        T = len(tests)
        plan.deadline = (req.deadline if self.config.enforce_deadlines
                         else None)
        t0 = time.perf_counter()
        try:
            with self._aot_export_scope():
                observed = np.asarray(engine.observed(), dtype=np.float64)
                # fold the T axis into the monitor's cell axis — the
                # MultiTestEngine adaptive convention (a module retires
                # only when settled in every cohort); the ceiling monitor
                # rides the same shape for fixed-n requests
                obs_cells = np.moveaxis(observed, 0, 1).reshape(plan.k, -1)
                monitor = PackMonitor([plan], obs_cells)
                if self.tel is not None:
                    monitor.enable_cost_tracking()
                nulls, completed, finished = engine.run_null_monitored(
                    plan.n_perm, plan.seed, monitor, telemetry=self.tel,
                    fault_policy=self._fault,
                )
        except BaseException:
            # same warm-pool hygiene as _execute_pack, same
            # BaseException rationale (ISSUE 12)
            self.pool.discard(key)
            raise
        self._account_pack_locked(
            time.perf_counter() - t0,
            0 if 0 in monitor.expired else min(int(completed), plan.n_perm),
        )
        mcosts = monitor.request_costs()
        mcost = (dict(mcosts["members"][0],
                      pack_totals=dict(mcosts["totals"]))
                 if mcosts is not None else None)
        if 0 in monitor.expired:
            # the T-axis request missed its deadline mid-run (multi-test
            # requests are their own pack, so there are no survivors)
            self._expire(req, monitor.expired[0],
                         min(int(monitor.folded), plan.n_perm),
                         cost=mcost)
            return
        total_space = pv.total_permutations(plan.pool.size, plan.sizes)
        per_test = []
        for ti in range(T):
            obs_t = observed[ti]
            nulls_t = nulls[ti][: plan.n_perm]
            if plan.adaptive:
                p_values, n_used = pv.sequential_pvalues(
                    obs_t, nulls_t, plan.alternative,
                    total_nperm=total_space,
                )
            else:
                p_values = pv.permutation_pvalues(
                    obs_t, nulls_t, plan.alternative,
                    total_nperm=total_space,
                )
                n_used = None
            hi, lo, eff = pv.tail_counts(obs_t, nulls_t)
            per_test.append({
                "test": mp.test_names[ti],
                "observed": obs_t, "p_values": p_values,
                "counts_hi": hi, "counts_lo": lo, "counts_eff": eff,
                "n_perm_used": n_used,
            })
        result = {
            **({"cost": mcost} if mcost is not None else {}),
            "module_labels": list(plan.labels),
            "tests": per_test,
            "n_perm": int(plan.n_perm),
            "completed": min(int(completed), plan.n_perm),
            "p_type": "sequential" if plan.adaptive else "fixed",
            "alternative": plan.alternative,
            "seed": int(plan.seed),
            "total_space": total_space,
            "finished": bool(finished),
        }
        self._finish(req, result, None, pack_id, 1, hit)

    # -- ops surface -------------------------------------------------------

    def stats(self) -> dict:
        now = time.monotonic()
        uptime = now - self._started_m
        with self._work:
            return {
                "tenants": {
                    n: {
                        "weight": t.weight,
                        "queue_depth": len(t.pending),
                        **t.counters,
                        # observability rollups (ISSUE 13): the tenant
                        # rows `top` renders — pinned-bucket latency
                        # quantiles, attributed device time (total and
                        # per wall-second), and the SLO burn rate
                        "p50_s": t.lat_hist.quantile(0.50),
                        "p99_s": t.lat_hist.quantile(0.99),
                        "latency_hist": t.lat_hist.state(),
                        "cost": dict(t.cost),
                        "device_s_per_s": (
                            t.cost["device_s"] / uptime if uptime > 0
                            else 0.0
                        ),
                        "burn_rate": self._burn_rate_locked(t, now),
                    }
                    for n, t in self._tenants.items()
                },
                "inflight": self._inflight,
                "accepting": self._accepting,
                "brownout": self._brownout,
                # fleet-admission inputs (ISSUE 14): the coordinator
                # aggregates these across replicas to make brownout/shed
                # decisions fleet-wide — queued permutation backlog plus
                # this replica's steady-state rate estimate (measured,
                # else the shared perf ledger's serve history)
                "backlog_perms": sum(
                    self._req_nperm(r)
                    for t in self._tenants.values() for r in t.pending
                ),
                "rate_pps": self._rate_pps(),
                # autoscaler idle signal (ISSUE 19): zero while anything
                # is queued or running, else seconds since the last
                # accepted/finished request
                "idle_s": (
                    0.0 if (self._inflight or self._any_pending_locked())
                    else max(0.0, now - self._last_active_m)
                ),
                # roofline gauge (ISSUE 18): this replica's most recent
                # engine run's achieved fraction of speed of light (null
                # on unknown device kinds / before the first telemetry-on
                # run) — the coordinator copies it into its per-replica
                # rows and `top` shows it as the util column
                "utilisation": (
                    (self._roofline_note() or {}).get("utilisation")
                ),
                "fleet_label": self.config.fleet_label,
                "journal": self.config.journal,
                "pool": self.pool.stats(),
                "packs": self._pack_seq,
                "uptime_s": uptime,
                "slo_s": self.config.slo_s,
                "slo_budget": self.config.slo_budget,
                "slo_window_s": self.config.slo_window_s,
            }

    def metrics_text(self) -> str:
        """Prometheus text exposition: the telemetry registry (when a bus
        is attached) plus per-tenant labeled serving series — the
        `/metrics`-style scrape surface the daemon exposes."""
        parts = []
        if self.tel is not None:
            parts.append(self.tel.metrics.render_prometheus())
        lines = []
        st = self.stats()
        lines.append("# TYPE netrep_serve_requests_total counter")
        for name, t in sorted(st["tenants"].items()):
            for outcome in ("received", "done", "failed", "rejected",
                            "expired", "deduped"):
                lines.append(
                    f'netrep_serve_requests_total{{tenant="{name}",'
                    f'outcome="{outcome}"}} {t[outcome]}'
                )
        lines.append("# TYPE netrep_serve_brownout gauge")
        lines.append(f'netrep_serve_brownout {int(st["brownout"])}')
        lines.append("# TYPE netrep_serve_queue_depth gauge")
        for name, t in sorted(st["tenants"].items()):
            lines.append(
                f'netrep_serve_queue_depth{{tenant="{name}"}} '
                f'{t["queue_depth"]}'
            )
        lines.append("# TYPE netrep_serve_pool_hits_total counter")
        lines.append(f'netrep_serve_pool_hits_total {st["pool"]["hits"]}')
        lines.append("# TYPE netrep_serve_pool_misses_total counter")
        lines.append(
            f'netrep_serve_pool_misses_total {st["pool"]["misses"]}'
        )
        lines.append("# TYPE netrep_serve_packs_total counter")
        lines.append(f'netrep_serve_packs_total {st["packs"]}')
        # per-tenant latency + attributed-cost series (ISSUE 13): PINNED
        # bucket boundaries (tm.LATENCY_BUCKETS_S / tm.COST_BUCKETS_S —
        # golden-shaped in tests/test_telemetry.py); burn rate = miss
        # fraction over the sliding window / error budget
        with self._work:
            tenants = [(n, self._tenants[n]) for n in sorted(self._tenants)]
            now = time.monotonic()
            lines.append("# TYPE netrep_serve_latency_seconds histogram")
            for name, t in tenants:
                lines.extend(t.lat_hist.prom_lines(
                    "netrep_serve_latency_seconds", f'tenant="{name}"'
                ))
            lines.append(
                "# TYPE netrep_serve_request_device_seconds histogram"
            )
            for name, t in tenants:
                lines.extend(t.cost_hist.prom_lines(
                    "netrep_serve_request_device_seconds",
                    f'tenant="{name}"'
                ))
            for metric, key, kind in (
                ("netrep_serve_attributed_device_seconds_total",
                 "device_s", "counter"),
                ("netrep_serve_attributed_perms_total", "perms",
                 "counter"),
                ("netrep_serve_attributed_bytes_to_host_total",
                 "bytes_to_host", "counter"),
            ):
                lines.append(f"# TYPE {metric} {kind}")
                for name, t in tenants:
                    lines.append(
                        f'{metric}{{tenant="{name}"}} {t.cost[key]:g}'
                    )
            lines.append("# TYPE netrep_serve_slo_burn_rate gauge")
            for name, t in tenants:
                lines.append(
                    f'netrep_serve_slo_burn_rate{{tenant="{name}"}} '
                    f"{self._burn_rate_locked(t, now):g}"
                )
        parts.append("\n".join(lines) + "\n")
        return "".join(parts)
