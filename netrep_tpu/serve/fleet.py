"""Fleet serving: replicated daemons behind one coordinator (ISSUE 14).

One daemon is one process; the ROADMAP's "millions of users" needs N
replicas behind a router. Everything below leans on invariants earlier
PRs made load-bearing — requests are durable + idempotent (the PR 10
journal), served results are bit-identical to direct calls under ANY
pack/replica composition (PR 7), checkpoints are identity-keyed and
composition-independent (PR 6/10), and traces survive restarts (PR 13)
— so serve work is *migratable by construction*; this module is the
robustness layer that actually migrates it when a replica dies.

Architecture::

    client ── one socket ──► FleetCoordinator ──► replica r0 (journal J0)
                              │  consistent-hash   replica r1 (journal J1)
                              │  ring on dataset    ...
                              │  digests            replica rN
                              ├─ JournalShipper per replica: J_i tails to
                              │  the designated peer's copy (acked
                              │  offsets, torn-line tolerant)
                              └─ heartbeat/health loop → failover

- **Routing**: (discovery digest, test digest) consistent-hashes onto
  the replica ring — the same dataset pair always lands on the same
  replica, so its warm ``ProgramPool`` engines keep hitting. Client ops
  route transparently: idempotency keys and trace ids pass through
  unchanged; registrations broadcast to every replica (cheap, bounded by
  dataset count — and the precondition for rebalance/failover, since any
  replica may inherit any pair).
- **Journal shipping**: each replica's write-ahead journal continuously
  ships to a designated peer (:class:`~netrep_tpu.serve.journal
  .JournalShipper` — fsynced segment tailing with acked offsets). On one
  host the copy is a file the peer replays; in a multi-host deployment
  the same protocol lands the copy on the peer's disk.
- **Failover**: the health loop declares a replica dead (worker exit /
  missed heartbeats), removes it from the ring (``replica_lost`` +
  ``ring_rebalanced`` — placement moves for the dead replica's keys
  ONLY, never a recompute), runs a final ship pass, and has the peer
  ``adopt_journal`` the shipped copy — the existing ``--recover`` replay
  (re-register datasets, answer duplicates from journaled results,
  re-queue unfinished requests, resume packs from the SHARED
  checkpoint directory at their last chunk boundary). Counts, p-values
  and adaptive decisions stay BIT-IDENTICAL to an undisturbed
  single-replica run, because every recompute path already is.
- **Fleet-wide admission**: brownout decisions read the AGGREGATE
  backlog-drain estimate — queued permutations summed across replicas
  over the summed per-replica rate estimates (measured, else the shared
  perf ledger's serve history) — so one hot replica does not brown out
  an idle fleet, and a drowning fleet sheds with an honest
  ``retry_after_s`` hint.

Surfaces: :func:`build_inprocess_fleet` (tier-1 tests, the load
generator — CPU-only, socket-free, exactly like ``InProcessClient`` vs
the daemon), and ``python -m netrep_tpu serve --fleet N --socket PATH``
(:func:`fleet_daemon` — coordinator process + N replica daemons).
``python -m netrep_tpu chaos --fleet`` is the one-command drill:
mid-pack replica SIGKILL → failover → parity gate → timeline.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import logging
import os
import socket as _socket
import threading
import time
import uuid

logger = logging.getLogger("netrep_tpu")

from ..utils import telemetry as tm
from . import lifecycle as lc
from .journal import JournalShipper
from .lifecycle import ReplicaLifecycle
from .scheduler import (
    PreservationServer, QueueFull, ServeConfig, ServeError,
)


class ReplicaLost(ServeError):
    """The replica holding this request died mid-flight. The coordinator
    catches this, waits for failover to complete, and re-routes under the
    SAME idempotency key — the peer either attaches to the adopted
    (re-queued) computation or answers from the shipped journal, so the
    one-computation-per-key contract survives the loss."""


class HashRing:
    """Consistent-hash ring with virtual nodes: dataset-pair digests map
    to replicas such that membership changes move ONLY the keys owned by
    the joining/leaving replica (the rebalance-is-a-ring-update,
    never-a-recompute contract, pinned in tests/test_serve_fleet.py).
    Deterministic — no RNG, placement is a pure function of (members,
    key)."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._points: list[tuple[int, str]] = []   # sorted (hash, rid)

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(s.encode(), digest_size=8).digest(), "big"
        )

    def add(self, rid: str) -> None:
        for i in range(self.vnodes):
            bisect.insort(self._points, (self._hash(f"{rid}#{i}"), rid))

    def remove(self, rid: str) -> None:
        self._points = [p for p in self._points if p[1] != rid]

    def members(self) -> set[str]:
        return {rid for _h, rid in self._points}

    def route(self, key: str) -> str | None:
        """The replica owning ``key``: first ring point at or past the
        key's hash, wrapping at the top."""
        if not self._points:
            return None
        h = self._hash(key)
        i = bisect.bisect_left(self._points, (h, ""))
        if i >= len(self._points):
            i = 0
        return self._points[i][1]

    def successor(self, rid: str) -> str | None:
        """The next DISTINCT replica clockwise from ``rid``'s first
        point — the designated journal-ship peer."""
        if not self._points:
            return None
        first = None
        for h, r in self._points:
            if r == rid:
                first = h
                break
        if first is None:
            return None
        n = len(self._points)
        i = bisect.bisect_right(self._points, (first, rid))
        for step in range(n):
            r = self._points[(i + step) % n][1]
            if r != rid:
                return r
        return None


@dataclasses.dataclass
class FleetConfig:
    """Coordinator knobs (transport-independent — shared by the
    in-process fleet and the daemon fleet)."""

    #: heartbeat/health-loop poll interval; a replica is declared dead on
    #: the first failed liveness check (the checks are cheap and the
    #: workers fail hard — SIGKILL or SimulatedCrash — so one strike is
    #: the honest policy; a flapping transport belongs behind retries in
    #: the replica handle, not here)
    heartbeat_s: float = 0.25
    #: journal-ship tail interval per replica
    ship_interval_s: float = 0.2
    #: virtual nodes per replica on the hash ring
    vnodes: int = 64
    #: fleet-wide brownout: shed new admissions when the AGGREGATE
    #: backlog drain estimate exceeds this (None = off); exit below
    #: ``brownout_exit_s`` (default half — same hysteresis contract as
    #: the per-replica brownout)
    brownout_enter_s: float | None = None
    brownout_exit_s: float | None = None
    #: assumed per-replica steady rate before anything is measured
    #: (else each replica's own estimate, else the shared perf ledger)
    rate_pps: float | None = None
    #: where shipped journal copies live: ``<fleet_dir>/ship/<rid>.jsonl``
    fleet_dir: str | None = None
    #: coordinator telemetry (fleet events land here): path / Telemetry /
    #: True / None — same resolution as ``ServeConfig.telemetry``
    telemetry: object = None
    #: bound on each replica's drain when the fleet closes
    drain_timeout_s: float = 120.0
    #: how long a re-routed request waits for an in-progress failover
    failover_wait_s: float = 60.0


class InProcessReplica:
    """One in-process fleet replica: a journaled
    :class:`PreservationServer` plus the liveness/kill seams the
    coordinator drives — the tier-1 fleet surface (CPU-only, socket-free
    by design, exactly like ``InProcessClient`` vs the socket daemon)."""

    def __init__(self, rid: str, server: PreservationServer,
                 generation: int = 0):
        self.rid = rid
        self.server = server
        self.journal_path = server.config.journal
        #: the explicit state machine (ISSUE 19) every membership change
        #: routes through — the coordinator drives the transitions
        self.lifecycle = ReplicaLifecycle(rid, generation=generation)
        #: set by the coordinator once failover for this replica is
        #: underway — in-flight ``analyze`` waiters stop waiting on the
        #: dead worker and re-route (the Event IS the synchronization)
        self.dead = threading.Event()

    def alive(self) -> bool:
        w = self.server._worker
        return w is not None and w.is_alive() and not self.dead.is_set()

    def arm_fault_plan(self, policy) -> None:
        """Drill hook (tests, ``serve_load --fleet``): arm a fault
        policy — e.g. ``FaultPolicy(plan="crash@24")``, the in-process
        SIGKILL stand-in — on the live server. The drills route first,
        then arm the replica that owns the pair, so the kill lands on
        the replica actually serving."""
        from ..utils.faults import resolve_runtime

        self.server._fault = resolve_runtime(policy)

    # -- ops ---------------------------------------------------------------

    def register_tenant(self, name: str, weight: int = 1) -> None:
        self.server.register_tenant(name, weight)

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        return self.server.register_dataset(tenant, name, **kw)

    def register_fixture(self, tenant: str, prefix: str = "fx",
                         **kw) -> dict:
        return self.server.register_fixture(tenant, prefix, **kw)

    def analyze(self, tenant: str, discovery: str, test, *,
                timeout: float | None = None, **kw) -> dict:
        """Blocking analyze that stays responsive to replica death: the
        wait polls so a mid-flight loss raises :class:`ReplicaLost`
        instead of blocking on a request whose worker no longer exists."""
        handle = self.server.submit(tenant, discovery, test, **kw)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not handle.done.wait(0.1):
            if self.dead.is_set():
                raise ReplicaLost(
                    f"replica {self.rid} died while serving the request"
                )
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"request did not finish on replica {self.rid}"
                )
        if getattr(handle, "requeued_on_drain", False):
            # a bounded drain (eviction grace) journaled this request as
            # requeued instead of finishing it — inside a fleet that is
            # a migration, not a failure: the peer adopts the journaled
            # record, so re-route under the same idempotency key
            raise ReplicaLost(
                f"replica {self.rid} drained away mid-request; the "
                f"journaled record migrates with the handoff"
            )
        return self.server.wait(handle, timeout=0)

    def adopt_journal(self, path: str, datasets_only: bool = False):
        return self.server.adopt_journal(path,
                                         datasets_only=datasets_only)

    def stats(self) -> dict:
        return self.server.stats()

    def metrics_text(self) -> str:
        return self.server.metrics_text()

    def close(self, drain: bool = True,
              timeout: float | None = None) -> None:
        self.server.close(drain=drain, timeout=timeout)


def _wire_line(path: str, payload: dict, timeout: float = 600.0) -> dict:
    """One raw request/response line over a unix socket — the
    coordinator's transparent proxy primitive: the client's op forwards
    VERBATIM (idempotency keys and trace ids pass through unchanged) and
    the replica's response returns verbatim."""
    s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(path)
        s.sendall((json.dumps(payload) + "\n").encode("utf-8"))
        f = s.makefile("r", encoding="utf-8")
        line = f.readline()
        if not line:
            raise ConnectionError("replica closed the connection")
        return json.loads(line)
    finally:
        s.close()


class DaemonReplica:
    """Subprocess replica handle: a ``python -m netrep_tpu serve``
    daemon on its own unix socket. Each op opens a short-lived
    connection (unix connects are microseconds and per-op connections
    keep the proxy thread-safe without a connection pool)."""

    def __init__(self, rid: str, socket_path: str, journal_path: str,
                 proc=None, timeout: float = 600.0,
                 generation: int = 0):
        self.rid = rid
        self.socket_path = socket_path
        self.journal_path = journal_path
        self.proc = proc
        self.timeout = timeout
        self.lifecycle = ReplicaLifecycle(rid, generation=generation)
        self.dead = threading.Event()

    def forward(self, op: dict) -> dict:
        """Raw proxy: the op dict forwards verbatim, the response comes
        back verbatim (whatever ``ok`` it carries)."""
        return _wire_line(self.socket_path, op, self.timeout)

    def request(self, op_name: str, **kw) -> dict:
        resp = self.forward({"op": op_name, **kw})
        if not resp.get("ok", False):
            raise ServeError(
                f"replica {self.rid} {op_name}: "
                f"{resp.get('error', 'unknown error')}"
            )
        return resp

    def alive(self) -> bool:
        if self.dead.is_set():
            return False
        if self.proc is not None and self.proc.poll() is not None:
            return False
        try:
            # short-fused ping: liveness must answer in heartbeats, not
            # the data-plane timeout — a wedged-but-listening daemon is
            # as dead as a closed socket
            resp = _wire_line(self.socket_path, {"op": "ping"},
                              timeout=2.0)
            return bool(resp.get("pong"))
        except (OSError, ConnectionError, ValueError):
            return False

    # -- ops ---------------------------------------------------------------

    def register_tenant(self, name: str, weight: int = 1) -> None:
        # the wire surface creates tenants implicitly at weight 1; an
        # explicit weight has no wire op — acceptable for daemon fleets
        pass

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        from .client import SocketClient

        c = SocketClient(self.socket_path, timeout=self.timeout)
        try:
            return c.register_dataset(tenant, name, **kw)
        finally:
            c.close()

    def register_fixture(self, tenant: str, prefix: str = "fx",
                         **kw) -> dict:
        from .client import SocketClient

        c = SocketClient(self.socket_path, timeout=self.timeout)
        try:
            return c.register_fixture(tenant, prefix, **kw)
        finally:
            c.close()

    def analyze(self, tenant: str, discovery: str, test, *,
                timeout: float | None = None, **kw) -> dict:
        from .client import SocketClient

        try:
            c = SocketClient(self.socket_path,
                             timeout=timeout or self.timeout)
        except OSError as e:
            raise ReplicaLost(f"replica {self.rid} unreachable") from e
        try:
            return c.analyze(tenant, discovery, test, **kw)
        except (ConnectionError, OSError) as e:
            raise ReplicaLost(
                f"replica {self.rid} died while serving the request"
            ) from e
        finally:
            try:
                c.close()
            except OSError:
                pass

    def adopt_journal(self, path: str, datasets_only: bool = False):
        return self.request("adopt_journal", path=path,
                            datasets_only=datasets_only).get("adopted")

    def stats(self) -> dict:
        return self.request("stats")["stats"]

    def metrics_text(self) -> str:
        return self.request("metrics")["text"]

    def kill(self) -> None:
        """SIGKILL the replica process (drills)."""
        import signal

        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGKILL)

    def close(self, drain: bool = True,
              timeout: float | None = None) -> None:
        import subprocess

        timeout = 120.0 if timeout is None else timeout
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            if drain:
                self.forward({"op": "shutdown"})
        except (OSError, ConnectionError, ValueError):
            pass
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            pass   # escalate: SIGTERM, then SIGKILL below
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


class FleetCoordinator:
    """The fleet control plane: consistent-hash routing, per-replica
    journal shipping, the heartbeat/health loop, replica-kill failover,
    and fleet-wide admission (module docstring). Transport-independent:
    replica handles are :class:`InProcessReplica` (tier-1 tests, load
    generator) or :class:`DaemonReplica` (the ``serve --fleet``
    daemon)."""

    def __init__(self, replicas, config: FleetConfig | None = None,
                 start: bool = True):
        self.config = config or FleetConfig()
        self.tel, self._tel_owned = tm.resolve_arg(self.config.telemetry)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._health: threading.Thread | None = None
        self._replicas: dict[str, object] = {}
        self._dead: set[str] = set()
        #: replicas mid-drain (retire / eviction handoff): off the ring
        #: and invisible to the health loop, but not yet dead
        self._draining: set[str] = set()
        #: the last drained replica's shipped journal copy — the
        #: persistent state a scale-to-zero fleet spawns back from
        self.last_journal: str | None = None
        #: attached :class:`Autoscaler` (None = static fleet); an empty
        #: fleet then spawns on demand instead of rejecting
        self.autoscaler = None
        self._ring = HashRing(self.config.vnodes)
        self._shippers: dict[str, JournalShipper] = {}
        self._peers: dict[str, str] = {}
        self._digests: dict[tuple[str, str], str] = {}
        self._fo_done: dict[str, threading.Event] = {}
        self._brownout = False
        self._ledger_rate: float | None = None
        self._ledger_rate_read = False
        self._started_m = time.monotonic()
        #: optional post-failover hook (e.g. the daemon fleet's respawn);
        #: called OUTSIDE the lock as ``on_failover(rid, peer_rid)``
        self.on_failover = None
        self._serve_sid = None
        if self.tel is not None:
            self._serve_sid = self.tel.begin_span(
                "serve_start", fleet=True, replicas=len(replicas),
            )
        for rep in replicas:
            self.join(rep)
        if start:
            self.start()

    # -- membership --------------------------------------------------------

    def join(self, rep) -> None:
        """Admit a replica to the ring (boot, dynamic join, or respawn):
        ring update + shipper start + ``replica_joined``/
        ``ring_rebalanced`` — placement moves for the new replica's keys
        only, never a recompute. Routes through the lifecycle machine:
        a spawning replica becomes ``ready`` here."""
        with self._lock:
            self._replicas[rep.rid] = rep
            self._dead.discard(rep.rid)
            self._draining.discard(rep.rid)
            self._ring.add(rep.rid)
            self._fo_done[rep.rid] = threading.Event()
            self._assign_peers_locked()
            members = sorted(self._ring.members())
        cycle = getattr(rep, "lifecycle", None)
        if cycle is not None:
            cycle.bind(self.tel, self._serve_sid)
            if cycle.state == lc.SPAWNING:
                cycle.transition(lc.READY, reason="join")
        if self.tel is not None:
            self.tel.emit("replica_joined", replica=rep.rid,
                          parent=self._serve_sid,
                          journal=rep.journal_path)
            self.tel.emit("ring_rebalanced", replica=rep.rid,
                          parent=self._serve_sid, reason="join",
                          members=",".join(members))

    def _assign_peers_locked(self) -> None:
        """(Re-)designate each live replica's ship peer (ring successor)
        and make sure its shipper exists. The shipped copy's PATH is
        canonical per source (``ship/<rid>.jsonl``) so re-designation on
        membership change costs nothing — on one host the copy is a
        file; a multi-host deployment ships the same protocol to the
        peer's disk."""
        for rid, rep in self._replicas.items():
            if rid in self._dead or rid in self._draining:
                continue
            self._peers[rid] = self._ring.successor(rid)
            if rid not in self._shippers and rep.journal_path:
                shipper = JournalShipper(
                    rep.journal_path, self._ship_dest(rid),
                    interval_s=self.config.ship_interval_s,
                    replica=rid, telemetry=self.tel,
                )
                self._shippers[rid] = shipper
                if not self._stop.is_set():
                    shipper.start()

    def _ship_dest(self, rid: str) -> str:
        base = self.config.fleet_dir or os.path.join(
            os.getcwd(), "netrep_fleet"
        )
        return os.path.join(base, "ship", f"{rid}.jsonl")

    def _collect_bundle(self, rid: str, reason: str) -> str | None:
        """Auto-collect the departed replica's diagnostic bundle
        (ISSUE 20): flight ring, env, and the shipped journal copy's
        REDACTED tail, under ``<fleet_dir>/bundles/``. Loud-never-fatal —
        forensics must never block a failover or handoff."""
        base = self.config.fleet_dir or os.path.join(
            os.getcwd(), "netrep_fleet"
        )
        from ..utils import bundle

        try:
            path = bundle.collect(
                os.path.join(base, "bundles",
                             f"netrep-bundle-{reason}-{rid}"),
                reason=reason, telemetry=self.tel,
                journal=self._ship_dest(rid),
            )
        # netrep: allow(exception-taxonomy) — bundle collection is best-effort forensics; the fleet keeps serving either way
        except Exception:
            logger.warning("fleet: bundle collection for departed "
                           "replica %s failed", rid, exc_info=True)
            return None
        logger.info("fleet: collected diagnostic bundle for %s at %s",
                    rid, path)
        return path

    def live_replicas(self) -> dict[str, object]:
        """Replicas still serving: not dead, not mid-drain (a draining
        replica is off the ring and counts as departed capacity)."""
        with self._lock:
            return {rid: rep for rid, rep in self._replicas.items()
                    if rid not in self._dead
                    and rid not in self._draining}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._health is not None:
                return
            self._health = threading.Thread(
                target=self._health_loop, name="netrep-fleet-health",
                daemon=True,
            )
            self._health.start()

    def close(self, drain: bool = True) -> None:
        """Stop the autoscaler and health loop, stop the shippers
        (final ship pass), drain every live replica through the
        lifecycle machine, close the coordinator span/bus."""
        scaler = self.autoscaler
        if scaler is not None:
            scaler.stop()
        self._stop.set()
        with self._lock:
            t, self._health = self._health, None
        if t is not None:
            t.join(timeout=10.0)
        with self._lock:
            shippers = list(self._shippers.values())
            self._shippers.clear()
            live = [rep for rid, rep in self._replicas.items()
                    if rid not in self._dead
                    and rid not in self._draining]
        for s in shippers:
            s.stop(final_flush=True)
        for rep in live:
            cycle = getattr(rep, "lifecycle", None)
            if cycle is not None and cycle.state == lc.READY:
                cycle.transition(lc.DRAINING, reason="fleet_close")
            rep.close(drain=drain, timeout=self.config.drain_timeout_s)
            if cycle is not None and cycle.state in (lc.DRAINING,
                                                     lc.SPAWNING):
                cycle.transition(lc.DEAD, reason="drained")
        if self.tel is not None:
            self.tel.end_span(
                self._serve_sid, "serve_end", fleet=True,
                drained=bool(drain),
                s=time.monotonic() - self._started_m,
            )
            if self._tel_owned:
                self.tel.close()

    # -- health / failover -------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.config.heartbeat_s):
            with self._lock:
                live = [(rid, rep)
                        for rid, rep in self._replicas.items()
                        if rid not in self._dead
                        and rid not in self._draining]
            for rid, rep in live:
                if self._stop.is_set():
                    return
                if not rep.alive():
                    self._failover(rid)

    def _failover(self, rid: str) -> None:
        """Replica death → journal-ship catch-up → peer adoption. The
        peer's ``adopt_journal`` runs the ordinary ``--recover`` replay
        over the shipped copy: duplicates answer from journaled results,
        unfinished requests re-queue in original order and resume their
        packs from the SHARED checkpoint directory — bit-identical by
        the same contracts boot recovery is."""
        t0 = time.perf_counter()
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rid in self._dead:
                return
            self._dead.add(rid)
            self._ring.remove(rid)
            shipper = self._shippers.pop(rid, None)
            peer_rid = self._peers.pop(rid, None)
            if peer_rid is None or peer_rid in self._dead:
                peer_rid = self._ring.route(rid)   # any survivor
            peer = (self._replicas.get(peer_rid)
                    if peer_rid is not None else None)
            self._assign_peers_locked()
            members = sorted(self._ring.members())
            done_evt = self._fo_done.get(rid)
        cycle = getattr(rep, "lifecycle", None)
        if cycle is not None and cycle.state != lc.DEAD:
            cycle.transition(lc.DEAD, reason="lost")
        if self.tel is not None:
            self.tel.emit("replica_lost", replica=rid,
                          parent=self._serve_sid, peer=peer_rid)
            self.tel.emit("failover_start", replica=rid,
                          parent=self._serve_sid, peer=peer_rid)
        if shipper is not None:
            # final catch-up: everything the dead replica fsynced before
            # its last breath reaches the copy (torn tail excluded, as
            # always). In a multi-host fleet this pass is a no-op — the
            # copy already holds exactly what was acked.
            shipper.stop(final_flush=True)
        with self._lock:
            self.last_journal = self._ship_dest(rid)
        summary = None
        if peer is not None:
            try:
                summary = peer.adopt_journal(self._ship_dest(rid))
            except (ServeError, OSError) as e:
                logger.warning("fleet failover: peer %s failed to adopt "
                               "%s's journal: %s", peer_rid, rid, e)
        rep.dead.set()
        if done_evt is not None:
            done_evt.set()
        if self.tel is not None:
            self.tel.emit(
                "failover_done", replica=rid, parent=self._serve_sid,
                peer=peer_rid, s=time.perf_counter() - t0,
                requeued=(summary or {}).get("requeued", 0),
                results=(summary or {}).get("results", 0),
            )
            self.tel.emit("ring_rebalanced", replica=rid,
                          parent=self._serve_sid, reason="leave",
                          members=",".join(members))
        self._collect_bundle(rid, "replica_failover")
        cb = self.on_failover
        if cb is not None:
            try:
                cb(rid, peer_rid)
            # netrep: allow(exception-taxonomy) — a broken respawn hook must not kill the health loop; the fleet keeps serving on the survivors
            except Exception:
                logger.warning("fleet on_failover hook failed",
                               exc_info=True)

    def await_failover(self, rid: str,
                       timeout: float | None = None) -> bool:
        """Block until failover for ``rid`` has completed (the peer has
        adopted its journal) — what a re-routing request waits on before
        retrying under its idempotency key."""
        with self._lock:
            evt = self._fo_done.get(rid)
        if evt is None:
            return True
        return evt.wait(timeout if timeout is not None
                        else self.config.failover_wait_s)

    def kill_replica(self, rid: str) -> None:
        """Drill helper: hard-kill a replica (SIGKILL for daemons; for
        in-process replicas the fault plan does the killing — this just
        triggers immediate detection instead of waiting a heartbeat)."""
        with self._lock:
            rep = self._replicas.get(rid)
        if rep is None:
            return
        kill = getattr(rep, "kill", None)
        if kill is not None:
            kill()
        self._failover(rid)

    # -- planned departures: retire + eviction handoff (ISSUE 19) ----------

    def ship_flush(self, rid: str) -> str | None:
        """Synchronously ship ``rid``'s journal tail and return the
        copy's path (None when the replica ships nothing) — what a
        freshly spawned replica adopts its registrations from."""
        with self._lock:
            shipper = self._shippers.get(rid)
        if shipper is None:
            return None
        shipper.flush()
        return self._ship_dest(rid)

    def _handoff(self, rid: str, *, reason: str,
                 grace_s: float | None = None) -> dict | None:
        """Planned departure — the shared core of autoscale retirement
        and the noticed-eviction handoff, the zero-recompute twin of
        :meth:`_failover`:

        1. ring removal FIRST (no new routes land on the leaver),
        2. bounded drain (in-flight and queued work finishes inside the
           grace; what cannot finish is journaled ``drain_requeued``),
        3. pre-ship of the journal tail (results + requeue marker reach
           the copy),
        4. peer adoption (duplicates answer from journaled results;
           anything requeued resumes from the SHARED checkpoint
           directory at its last chunk boundary — a handoff, never a
           recompute).

        Only THEN may the process be killed. Returns the handoff
        summary dict, or None when ``rid`` is not a live replica."""
        t0 = time.perf_counter()
        with self._lock:
            rep = self._replicas.get(rid)
            if (rep is None or rid in self._dead
                    or rid in self._draining):
                return None
            self._draining.add(rid)
            self._ring.remove(rid)
            shipper = self._shippers.pop(rid, None)
            peer_rid = self._peers.pop(rid, None)
            if peer_rid is None or peer_rid in self._dead:
                peer_rid = self._ring.route(rid)   # any survivor
            peer = (self._replicas.get(peer_rid)
                    if peer_rid is not None else None)
            self._assign_peers_locked()
            members = sorted(self._ring.members())
            done_evt = self._fo_done.get(rid)
        cycle = getattr(rep, "lifecycle", None)
        if cycle is not None and cycle.state == lc.READY:
            cycle.transition(lc.DRAINING, reason=reason)
        if self.tel is not None:
            self.tel.emit("ring_rebalanced", replica=rid,
                          parent=self._serve_sid, reason=reason,
                          members=",".join(members))
        rep.close(drain=True,
                  timeout=(grace_s if grace_s is not None
                           else self.config.drain_timeout_s))
        if shipper is not None:
            shipper.stop(final_flush=True)
        with self._lock:
            self.last_journal = self._ship_dest(rid)
        summary = None
        if peer is not None:
            try:
                summary = peer.adopt_journal(self._ship_dest(rid))
            except (ServeError, OSError) as e:
                logger.warning("fleet handoff: peer %s failed to adopt "
                               "%s's journal: %s", peer_rid, rid, e)
        with self._lock:
            self._dead.add(rid)
            self._draining.discard(rid)
        if cycle is not None and cycle.state != lc.DEAD:
            cycle.transition(lc.DEAD, reason="drained")
        rep.dead.set()
        if done_evt is not None:
            done_evt.set()
        if self.tel is not None and not members:
            self.tel.emit("scale_to_zero", replica=rid,
                          parent=self._serve_sid,
                          journal=self._ship_dest(rid))
        return {
            "replica": rid,
            "peer": peer_rid,
            "s": time.perf_counter() - t0,
            "requeued": (summary or {}).get("requeued", 0),
            "results": (summary or {}).get("results", 0),
        }

    def retire_replica(self, rid: str) -> dict | None:
        """Drain-and-retire one replica (the autoscaler's scale-down
        move): planned departure under the full drain timeout."""
        return self._handoff(rid, reason="retire")

    def evict_notice(self, rid: str, grace_s: float = 30.0) -> dict | None:
        """First-class eviction notice (wire op ``evict_notice`` /
        ``NETREP_FLEET_EVICT`` drill env): the capacity under ``rid``
        will be revoked in ``grace_s`` seconds. Runs the full handoff —
        ring removal, bounded drain, journal-tail pre-ship, peer
        adoption — BEFORE the kill, so a noticed eviction loses zero
        work and recomputes nothing; the SIGKILL drill (``chaos
        --fleet``) remains the unnoticed-eviction fallback. Returns the
        handoff summary (None when ``rid`` is not live)."""
        if self.tel is not None:
            self.tel.emit("evict_notice", replica=rid,
                          parent=self._serve_sid,
                          grace_s=float(grace_s))
        out = self._handoff(rid, reason="evict", grace_s=grace_s)
        if out is not None and self.tel is not None:
            self.tel.emit("evict_handoff_done", replica=rid,
                          parent=self._serve_sid, peer=out["peer"],
                          s=out["s"], requeued=out["requeued"],
                          results=out["results"])
        if out is not None:
            self._collect_bundle(rid, "replica_evicted")
        return out

    # -- routing -----------------------------------------------------------

    def _route_key(self, tenant: str, discovery: str, test) -> str:
        tests = list(test) if isinstance(test, (list, tuple)) else [test]
        with self._lock:
            parts = [
                self._digests.get((tenant, n), f"name:{tenant}:{n}")
                for n in [discovery, *tests]
            ]
        return "|".join(parts)

    def route(self, tenant: str, discovery: str, test):
        """The live replica owning this dataset pair (locality: same
        pair → same replica → warm pooled engines), or None when the
        fleet is empty."""
        key = self._route_key(tenant, discovery, test)
        with self._lock:
            rid = self._ring.route(key)
            return self._replicas.get(rid) if rid is not None else None

    def note_digest(self, tenant: str, name: str, digest: str) -> None:
        """Record a dataset's content digest for ring routing (the wire
        coordinator captures it from a broadcast ``register``
        response)."""
        with self._lock:
            self._digests[(tenant, name)] = str(digest)

    # -- fleet-wide admission ----------------------------------------------

    def _fallback_rate_locked(self) -> float | None:
        """Per-replica rate assumption: configured, else the shared perf
        ledger's serve/run history (read once, cached) — None when
        nothing is known (fleet brownout then stays off: no guessing)."""
        if self.config.rate_pps:
            return float(self.config.rate_pps)
        if not self._ledger_rate_read:
            self._ledger_rate_read = True
            try:
                from ..utils import perfledger

                entries = [
                    float(e["perms_per_sec"])
                    for e in perfledger.read_entries(
                        perfledger.default_path())
                    if e.get("source") in ("serve", "run")
                ][-8:]
                if entries:
                    self._ledger_rate = sorted(entries)[len(entries) // 2]
            except OSError:
                pass
        return self._ledger_rate

    def drain_estimate(self, extra_perms: int = 0) -> float | None:
        """AGGREGATE backlog drain estimate: queued permutations summed
        across live replicas over the summed per-replica rates — the
        fleet-wide admission signal. None when no rate is known."""
        backlog = extra_perms
        rate = 0.0
        unknown = 0
        for rep in self.live_replicas().values():
            try:
                st = rep.stats()
            except (ServeError, OSError, ConnectionError):
                continue
            backlog += int(st.get("backlog_perms", 0) or 0)
            r = st.get("rate_pps")
            if r:
                rate += float(r)
            else:
                unknown += 1
        if unknown:
            fb = None
            with self._lock:
                fb = self._fallback_rate_locked()
            if fb:
                rate += fb * unknown
        if rate <= 0:
            return None
        return backlog / rate

    def admit(self, extra_perms: int = 0) -> None:
        """Fleet-wide brownout gate, called before routing a new
        analyze: raises :class:`QueueFull` with the aggregate drain
        estimate as ``retry_after_s`` while browned out. Same hysteresis
        contract as the per-replica brownout (which still applies,
        per-tenant-weighted, at each replica behind this gate)."""
        cfg = self.config
        if cfg.brownout_enter_s is None:
            return
        est = self.drain_estimate(extra_perms)
        if est is None:
            return
        exit_s = (cfg.brownout_exit_s if cfg.brownout_exit_s is not None
                  else cfg.brownout_enter_s / 2.0)
        with self._lock:
            if not self._brownout and est > cfg.brownout_enter_s:
                self._brownout = True
                if self.tel is not None:
                    self.tel.emit("serve_brownout_enter", fleet=True,
                                  est_drain_s=float(est),
                                  parent=self._serve_sid)
            elif self._brownout and est < exit_s:
                self._brownout = False
                if self.tel is not None:
                    self.tel.emit("serve_brownout_exit", fleet=True,
                                  est_drain_s=float(est),
                                  parent=self._serve_sid)
            browned = self._brownout
        if browned:
            raise QueueFull(
                f"fleet is browned out (aggregate backlog drain "
                f"{est:.1f}s); retry later",
                retry_after_s=round(est, 3),
            )

    # -- client surface ----------------------------------------------------

    def register_tenant(self, name: str, weight: int = 1) -> None:
        for rep in self.live_replicas().values():
            rep.register_tenant(name, weight)

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        """Broadcast registration (every replica may inherit any pair on
        failover/rebalance); records the content digest for ring
        routing. Returns the digest — identical on every replica by the
        digest's content-addressed definition."""
        digest = None
        for rep in self.live_replicas().values():
            digest = rep.register_dataset(tenant, name, **kw)
        if digest is None:
            raise ServeError("no live replicas to register on")
        with self._lock:
            self._digests[(tenant, name)] = digest
        return digest

    def register_fixture(self, tenant: str, prefix: str = "fx",
                         **kw) -> dict:
        out = None
        for rep in self.live_replicas().values():
            out = rep.register_fixture(tenant, prefix, **kw)
        if out is None:
            raise ServeError("no live replicas to register on")
        return out

    def analyze(self, tenant: str, discovery: str, test, *,
                timeout: float | None = None, **kw) -> dict:
        """Blocking analyze through the fleet: admission gate → ring
        route → replica. A replica death mid-flight waits for failover
        and re-routes under the SAME idempotency key (set here when the
        caller sent none), so the retry attaches to the adopted
        computation or answers from the shipped journal — never a second
        computation."""
        kw.setdefault("idempotency_key", f"f-{uuid.uuid4().hex[:16]}")
        n_perm = int(kw.get("n_perm") or 0)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            self.admit(extra_perms=n_perm)
            rep = self.route(tenant, discovery, test)
            if rep is None:
                # scale-to-zero (ISSUE 19): an empty autoscaled fleet
                # spawns on demand and the request queues behind the
                # boot — never a rejection while under the brownout
                # threshold (the admit gate above still applies)
                scaler = self.autoscaler
                if scaler is not None and scaler.request_spawn():
                    if (deadline is not None
                            and time.monotonic() > deadline):
                        raise TimeoutError(
                            "request timed out waiting for a "
                            "spawn-on-demand replica"
                        )
                    time.sleep(0.05)
                    continue
                raise ServeError("fleet has no live replicas")
            left = (None if deadline is None
                    else max(0.1, deadline - time.monotonic()))
            try:
                return rep.analyze(tenant, discovery, test,
                                   timeout=left, **kw)
            except ReplicaLost:
                self.await_failover(rep.rid)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        "request did not finish before its timeout "
                        "(failover consumed the budget)"
                    ) from None
                continue

    # -- ops surface -------------------------------------------------------

    def stats(self) -> dict:
        """Fleet-level stats: one row per replica (alive/dead, backlog,
        rate, packs, per-tenant counters) plus merged per-tenant
        counters and the coordinator's admission state — what ``top``
        renders as the per-replica section."""
        with self._lock:
            reps = dict(self._replicas)
            dead = set(self._dead)
            brownout = self._brownout
            members = sorted(self._ring.members())
        rows = {}
        merged: dict[str, dict] = {}
        inflight = packs = 0
        for rid in sorted(reps):
            cycle = getattr(reps[rid], "lifecycle", None)
            state = cycle.state if cycle is not None else None
            gen = cycle.generation if cycle is not None else 0
            if rid in dead:
                rows[rid] = {"alive": False,
                             "state": state or "dead", "gen": gen}
                continue
            try:
                st = reps[rid].stats()
            except (ServeError, OSError, ConnectionError):
                rows[rid] = {"alive": False,
                             "state": state or "dead", "gen": gen}
                continue
            proc = getattr(reps[rid], "proc", None)
            rows[rid] = {
                "alive": True,
                "state": state or "ready",
                "gen": gen,
                "idle_s": st.get("idle_s"),
                "pid": proc.pid if proc is not None else None,
                "backlog_perms": st.get("backlog_perms", 0),
                "rate_pps": st.get("rate_pps"),
                "utilisation": st.get("utilisation"),
                "inflight": st.get("inflight", 0),
                "packs": st.get("packs", 0),
                "brownout": st.get("brownout", False),
                "queue_depth": sum(
                    t.get("queue_depth", 0)
                    for t in st.get("tenants", {}).values()
                ),
                "done": sum(t.get("done", 0)
                            for t in st.get("tenants", {}).values()),
            }
            inflight += int(st.get("inflight", 0) or 0)
            packs += int(st.get("packs", 0) or 0)
            for tn, t in st.get("tenants", {}).items():
                m = merged.setdefault(tn, {
                    "weight": t.get("weight", 1), "queue_depth": 0,
                    "received": 0, "done": 0, "failed": 0,
                    "rejected": 0, "expired": 0, "deduped": 0,
                    "cost": {"device_s": 0.0, "perms": 0,
                             "bytes_to_host": 0},
                    "burn_rate": 0.0,
                })
                for k in ("queue_depth", "received", "done", "failed",
                          "rejected", "expired", "deduped"):
                    m[k] += int(t.get(k, 0) or 0)
                c = t.get("cost") or {}
                m["cost"]["device_s"] += float(c.get("device_s", 0.0))
                m["cost"]["perms"] += int(c.get("perms", 0) or 0)
                m["cost"]["bytes_to_host"] += int(
                    c.get("bytes_to_host", 0) or 0)
                m["burn_rate"] = max(m["burn_rate"],
                                     float(t.get("burn_rate", 0.0)))
        return {
            "fleet": True,
            "replicas": rows,
            "ring": members,
            "tenants": merged,
            "brownout": brownout,
            "accepting": not self._stop.is_set(),
            "inflight": inflight,
            "packs": packs,
            "uptime_s": time.monotonic() - self._started_m,
        }

    def metrics_text(self) -> str:
        """Concatenated per-replica Prometheus expositions, each under a
        replica-identifying comment header."""
        parts = []
        for rid, rep in sorted(self.live_replicas().items()):
            try:
                parts.append(f"# fleet replica {rid}\n"
                             + rep.metrics_text())
            except (ServeError, OSError, ConnectionError):
                parts.append(f"# fleet replica {rid} unreachable\n")
        return "".join(parts)


# ---------------------------------------------------------------------------
# autoscaling (ISSUE 19)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AutoscaleConfig:
    """Autoscaler knobs. The scaling signal is the coordinator's
    AGGREGATE backlog-drain estimate (the same number the fleet-wide
    brownout reads), with brownout-style hysteresis: scale up above
    ``scale_up_drain_s``, allow scale-down only below
    ``scale_up_exit_s`` (default half) — plus a cooldown between
    actions and a per-replica idle requirement, so the loop never
    flaps."""

    #: spawn a replica when the aggregate drain estimate exceeds this
    scale_up_drain_s: float = 10.0
    #: hysteresis exit: retirement is only considered below this
    #: (None = half of ``scale_up_drain_s``)
    scale_up_exit_s: float | None = None
    #: retire a replica after it has been idle (no inflight work, no
    #: backlog) this long — measured on the autoscaler's own clock
    scale_down_idle_s: float = 30.0
    #: fleet-size bounds; ``min_replicas=0`` enables scale-to-zero
    min_replicas: int = 0
    max_replicas: int = 4
    #: minimum spacing between scaling actions (either direction)
    cooldown_s: float = 5.0
    #: control-loop poll interval (the threaded loop; tests drive
    #: :meth:`Autoscaler.tick` directly under a fake clock)
    tick_s: float = 0.25


class Autoscaler:
    """The closed loop that makes replicas cattle (ISSUE 19): grow the
    fleet when the aggregate backlog-drain estimate says the queue is
    outrunning capacity, drain-and-retire idle replicas (the PR 10
    bounded SIGTERM drain, through :meth:`FleetCoordinator
    .retire_replica`), and — with ``min_replicas=0`` — scale to zero,
    where the journal + the AOT warm store ARE the fleet state: a
    submission against the empty fleet triggers spawn-on-demand and
    queues behind the boot.

    ``spawn(index) -> replica`` is the capacity source (an in-process
    replica factory in tier-1 / the load generator, a
    :func:`spawn_replica_daemon` wrapper under ``serve --fleet
    --autoscale``). A freshly spawned replica adopts a live peer's
    shipped journal copy (datasets only) — or, from zero, the LAST
    drained replica's full copy — before it enters the ring, so it
    knows every registration and answers duplicates without recompute.

    Deterministic under test: ``clock`` is injectable and
    :meth:`tick` runs one decision pass synchronously — tier-1 drives
    it with a fake clock and ``start=False`` (no thread)."""

    def __init__(self, coord: FleetCoordinator, spawn,
                 config: AutoscaleConfig | None = None, *,
                 clock=time.monotonic, start: bool = True):
        self.coord = coord
        self._spawn_fn = spawn
        self.config = config or AutoscaleConfig()
        self._clock = clock
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_action: float | None = None
        self._spawning = False
        #: clock time each replica was last seen busy (first sight
        #: counts as busy — a replica must prove an idle PERIOD)
        self._last_busy: dict[str, float] = {}
        # the next spawn index must clear EVERY replica id the
        # coordinator has ever seen (dead ones included) — a fresh
        # spawn reusing a dead rid would collide in the ring, the ship
        # directory, and the telemetry fold
        seen = [int(rid[1:].split(".")[0])
                for rid in coord.stats().get("replicas", {})
                if rid.startswith("r")
                and rid[1:].split(".")[0].isdigit()]
        self._next_index = max(seen) + 1 if seen else 0
        coord.autoscaler = self
        if start:
            self.start()

    # -- loop lifecycle ----------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name="netrep-fleet-autoscale",
                daemon=True,
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self.tick()
            except (ServeError, OSError, ConnectionError):
                logger.warning("autoscaler tick failed", exc_info=True)

    # -- the control loop --------------------------------------------------

    def _cooldown_over(self, now: float) -> bool:
        return (self._last_action is None
                or now - self._last_action >= self.config.cooldown_s)

    def tick(self, now: float | None = None) -> str | None:
        """One decision pass: returns ``"up"``, ``"down"``, or None.
        Deterministic given the fleet's stats and the injected clock —
        the tier-1 contract."""
        cfg = self.config
        now = self._clock() if now is None else float(now)
        with self._lock:
            if self._spawning:
                return None
            cooldown_ok = self._cooldown_over(now)
        live = self.coord.live_replicas()
        # idle bookkeeping on the autoscaler's own clock: a replica is
        # busy while it has inflight work or queued backlog
        for rid, rep in live.items():
            try:
                st = rep.stats()
            except (ServeError, OSError, ConnectionError):
                continue
            busy = bool(st.get("inflight", 0)
                        or st.get("backlog_perms", 0))
            if busy or rid not in self._last_busy:
                self._last_busy[rid] = now
        for rid in list(self._last_busy):
            if rid not in live:
                del self._last_busy[rid]
        if not cooldown_ok:
            return None
        est = self.coord.drain_estimate()
        # below the floor (an eviction can sink the fleet under it):
        # restore capacity regardless of backlog
        if len(live) < cfg.min_replicas:
            if self._do_spawn(reason="min_replicas", est=est):
                return "up"
            return None
        if (est is not None and est > cfg.scale_up_drain_s
                and len(live) < cfg.max_replicas):
            if self._do_spawn(reason="backlog", est=est):
                return "up"
            return None
        exit_s = (cfg.scale_up_exit_s if cfg.scale_up_exit_s is not None
                  else cfg.scale_up_drain_s / 2.0)
        if (len(live) > cfg.min_replicas
                and (est is None or est < exit_s)):
            idle = [rid for rid in live
                    if now - self._last_busy.get(rid, now)
                    >= cfg.scale_down_idle_s]
            if idle:
                rid = sorted(idle)[-1]   # newest id retires first
                if self.coord.tel is not None:
                    self.coord.tel.emit(
                        "autoscale_down", replica=rid,
                        parent=self.coord._serve_sid,
                        idle_s=now - self._last_busy.get(rid, now),
                        replicas=len(live) - 1,
                    )
                self.coord.retire_replica(rid)
                with self._lock:
                    self._last_action = now
                return "down"
        return None

    # -- spawning ----------------------------------------------------------

    def request_spawn(self) -> bool:
        """Spawn-on-demand entry (the coordinator calls this when a
        request finds the fleet empty): True means a replica is coming
        (spawned here, already mid-spawn, or already joined) and the
        caller should keep queueing behind it; False means the
        autoscaler cannot add capacity (``max_replicas`` is 0)."""
        if self.coord.live_replicas():
            return True
        if self.config.max_replicas < 1:
            return False
        with self._lock:
            in_flight = self._spawning
        if in_flight:
            return True
        self._do_spawn(reason="empty_fleet", event="spawn_on_demand")
        return True

    def _do_spawn(self, *, reason: str, est: float | None = None,
                  event: str = "autoscale_up") -> bool:
        with self._lock:
            if self._spawning:
                return False
            self._spawning = True
            idx = self._next_index
            self._next_index += 1
        try:
            rep = self._spawn_fn(idx)
            # seed the newcomer BEFORE it enters the ring: a live
            # peer's shipped copy replays registrations (datasets
            # only — its pending work is its own); from zero, the last
            # drained replica's copy replays EVERYTHING, including
            # requests the drain journaled as requeued
            live = sorted(self.coord.live_replicas())
            src = (self.coord.ship_flush(live[0]) if live
                   else self.coord.last_journal)
            if src:
                try:
                    rep.adopt_journal(src, datasets_only=bool(live))
                except (ServeError, OSError) as e:
                    logger.warning("autoscale spawn: %s failed to adopt "
                                   "%s: %s", rep.rid, src, e)
            self.coord.join(rep)
            if self.coord.tel is not None:
                data = {"replica": rep.rid, "reason": reason,
                        "replicas": len(self.coord.live_replicas())}
                if est is not None:
                    data["est_drain_s"] = float(est)
                if event == "autoscale_up":
                    self.coord.tel.emit(
                        "autoscale_up", parent=self.coord._serve_sid,
                        **data)
                else:
                    self.coord.tel.emit(
                        "spawn_on_demand",
                        parent=self.coord._serve_sid, **data)
            return True
        finally:
            with self._lock:
                self._spawning = False
                self._last_action = self._clock()


# ---------------------------------------------------------------------------
# in-process fleet construction (tier-1 tests, load generator)
# ---------------------------------------------------------------------------


def _make_inprocess_replica(i: int, fleet_dir: str, make_config=None,
                            start_servers: bool = True) -> InProcessReplica:
    """One in-process replica in the fleet layout (``r<i>/journal
    .jsonl`` + the SHARED ``ckpt/``) — the construction
    :func:`build_inprocess_fleet` and :func:`inprocess_spawner`
    share."""
    ckpt_dir = os.path.join(fleet_dir, "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    rid = f"r{i}"
    rdir = os.path.join(fleet_dir, rid)
    os.makedirs(rdir, exist_ok=True)
    jpath = os.path.join(rdir, "journal.jsonl")
    if make_config is not None:
        cfg = make_config(rid, jpath, ckpt_dir)
    else:
        cfg = ServeConfig(journal=jpath, checkpoint_dir=ckpt_dir,
                          fleet_label=rid)
    return InProcessReplica(
        rid, PreservationServer(cfg, start=start_servers)
    )


def inprocess_spawner(fleet_dir: str, *, make_config=None,
                      start_servers: bool = True):
    """The :class:`Autoscaler` ``spawn`` callable for in-process
    fleets: ``spawn(index)`` boots ``r<index>`` into the same fleet
    layout (same shared checkpoint directory, same ``make_config``
    knobs) the static replicas use."""
    def spawn(index: int) -> InProcessReplica:
        return _make_inprocess_replica(index, fleet_dir, make_config,
                                       start_servers=start_servers)
    return spawn


def build_inprocess_fleet(
    n: int, fleet_dir: str, *, make_config=None,
    fleet_config: FleetConfig | None = None, start: bool = True,
    start_servers: bool = True,
) -> FleetCoordinator:
    """N in-process replicas under one coordinator — the socket-free
    fleet the tier-1 tests and ``serve_load --fleet`` drive.

    Layout under ``fleet_dir``: ``r<i>/journal.jsonl`` per replica,
    ``ship/`` for the shipped copies, and ONE SHARED ``ckpt/`` — pack
    checkpoint paths are keyed on member identity + engine config (not
    on the replica), so the peer adopting a dead replica's requests
    finds its mid-pack checkpoints exactly where the dead replica left
    them and resumes from the last chunk boundary.

    ``make_config(rid, journal_path, ckpt_dir) -> ServeConfig`` lets the
    caller inject per-replica knobs (the drills inject a fault plan into
    ONE replica this way); the default is a journaled CPU-deterministic
    config with ``fleet_label=rid``."""
    os.makedirs(os.path.join(fleet_dir, "ship"), exist_ok=True)
    ckpt_dir = os.path.join(fleet_dir, "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    if fleet_config is None:
        fleet_config = FleetConfig()
    if fleet_config.fleet_dir is None:
        fleet_config = dataclasses.replace(fleet_config,
                                           fleet_dir=fleet_dir)
    replicas = [
        _make_inprocess_replica(i, fleet_dir, make_config,
                                start_servers=start_servers)
        for i in range(int(n))
    ]
    return FleetCoordinator(replicas, fleet_config, start=start)


# ---------------------------------------------------------------------------
# daemon fleet (`python -m netrep_tpu serve --fleet N`)
# ---------------------------------------------------------------------------


def spawn_replica_daemon(rid: str, fleet_dir: str, args, *,
                         generation: int = 0, env_extra: dict | None = None):
    """Boot one replica daemon subprocess on its own socket, journaling
    into the fleet layout with the SHARED checkpoint directory.
    Respawns bump ``generation`` so a fresh journal never replays work
    the peer already adopted."""
    import subprocess
    import sys

    rdir = os.path.join(fleet_dir, rid)
    os.makedirs(rdir, exist_ok=True)
    suffix = f".g{generation}" if generation else ""
    sock = os.path.join(rdir, f"serve{suffix}.sock")
    jpath = os.path.join(rdir, f"journal{suffix}.jsonl")
    cmd = [
        sys.executable, "-m", "netrep_tpu", "serve",
        "--socket", sock, "--journal", jpath,
        "--checkpoint-dir", os.path.join(fleet_dir, "ckpt"),
        "--chunk", str(args.chunk),
        "--checkpoint-every", str(getattr(args, "checkpoint_every", 4096)),
        "--drain-timeout", str(args.drain_timeout),
        "--telemetry", os.path.join(rdir, f"tel{suffix}.jsonl"),
        "--fleet-label", rid,
    ]
    if args.n_perm:
        cmd += ["--n-perm", str(args.n_perm)]
    if args.brownout_enter_s is not None:
        cmd += ["--brownout-enter-s", str(args.brownout_enter_s)]
    # a replica never inherits the coordinator's fault plan or its
    # eviction drill — both address the FLEET, not the child process
    env = {k: v for k, v in os.environ.items()
           if k not in ("NETREP_FAULT_PLAN", "NETREP_FLEET_EVICT")}
    env.setdefault("JAX_PLATFORMS",
                   os.environ.get("JAX_PLATFORMS", "") or "cpu")
    # warm start (ISSUE 15): every replica generation — including a
    # respawn (r0.g1) — resolves the SAME AOT store path, so programs
    # one generation exported (fleet replicas export-on-miss via their
    # fleet_label) are the next generation's zero-compile boot
    from ..utils import aot

    store = aot.get_store()
    if store is not None:
        env.setdefault(aot.STORE_ENV, store.path)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=env)
    return DaemonReplica(rid, sock, jpath, proc=proc,
                         generation=generation)


def _wait_socket(rep: DaemonReplica, budget_s: float = 180.0) -> bool:
    deadline = time.monotonic() + budget_s
    while not os.path.exists(rep.socket_path):
        if (time.monotonic() > deadline
                or (rep.proc is not None
                    and rep.proc.poll() is not None)):
            return False
        time.sleep(0.1)
    return True


def dispatch_fleet_op(coord: FleetCoordinator, op: dict,
                      stop: threading.Event,
                      route_mode: str = "proxy") -> dict:
    """Execute one wire op against the coordinator. Registrations
    broadcast; ``analyze`` routes by the ring and PROXIES the op
    verbatim (idempotency keys and trace ids pass through unchanged) —
    or, under ``route_mode='redirect'``, answers with a ``redirect``
    hint naming the home replica's socket so the client takes its data
    plane there directly. Never raises."""
    from .server import _malformed

    if not isinstance(op, dict):
        return _malformed(coord, f"op must be a JSON object, "
                                 f"got {type(op).__name__}")
    try:
        kind = op.get("op")
        if kind == "ping":
            return {"ok": True, "pong": True, "fleet": True,
                    "replicas": sorted(coord.live_replicas())}
        if kind == "stats":
            return {"ok": True, "stats": coord.stats()}
        if kind == "metrics":
            return {"ok": True, "text": coord.metrics_text()}
        if kind == "shutdown":
            stop.set()
            return {"ok": True, "draining": True}
        if kind == "evict_notice":
            # noticed preemption (ISSUE 19): handoff, not failover —
            # the reply carries the handoff receipt (peer, seconds,
            # requeued/result counts) so drills can assert zero loss
            rid = str(op.get("replica") or "")
            if rid not in coord.live_replicas():
                return {"ok": False,
                        "error": f"no live replica {rid!r}"}
            grace = float(op.get("grace_s") or 30.0)
            summary = coord.evict_notice(rid, grace_s=grace)
            if summary is None:
                return {"ok": False,
                        "error": f"replica {rid!r} left before the "
                                 f"notice landed"}
            return {"ok": True, "evicted": rid, **summary}
        if kind in ("register", "register_fixture"):
            resp = None
            for rid, rep in sorted(coord.live_replicas().items()):
                fwd = getattr(rep, "forward", None)
                if fwd is None:
                    return {"ok": False, "error": "raw broadcast needs "
                                                  "daemon replicas"}
                resp = fwd(op)
                if not resp.get("ok", False):
                    return resp
            if resp is None:
                return {"ok": False, "error": "no live replicas"}
            if kind == "register" and resp.get("digest"):
                coord.note_digest(str(op.get("tenant")),
                                  str(op.get("name")),
                                  str(resp["digest"]))
            return resp
        if kind == "analyze":
            op.setdefault("idempotency_key",
                          f"f-{uuid.uuid4().hex[:16]}")
            try:
                coord.admit(extra_perms=int(op.get("n_perm") or 0))
            except QueueFull as e:
                resp = {"ok": False, "error": f"QueueFull: {e}",
                        "retryable": True}
                if e.retry_after_s is not None:
                    resp["retry_after_s"] = float(e.retry_after_s)
                return resp
            for _hop in range(8):   # bounded: re-routes per failover
                rep = coord.route(str(op.get("tenant")),
                                  str(op.get("discovery")),
                                  op.get("test"))
                if rep is None:
                    return {"ok": False, "error": "fleet has no live "
                                                  "replicas"}
                if (route_mode == "redirect"
                        and getattr(rep, "socket_path", None)):
                    # data-plane redirect: the client re-sends the SAME
                    # op (same key, same trace) straight to the home
                    # replica — the coordinator stays off the hot path
                    return {"ok": False, "retryable": True,
                            "redirect": rep.socket_path}
                fwd = getattr(rep, "forward", None)
                if fwd is None:
                    return {"ok": False,
                            "error": "proxy needs daemon replicas"}
                try:
                    resp = fwd(op)
                except (OSError, ConnectionError, ValueError):
                    coord.await_failover(rep.rid)
                    continue
                if (not resp.get("ok", False)
                        and "requeued-on-restart"
                        in str(resp.get("error", ""))):
                    # the home replica drained away (retire/evict,
                    # ISSUE 19) with this request still queued: the
                    # journaled record migrates with the handoff — wait
                    # for the peer to adopt it, then retry the SAME
                    # idempotency key there (dedup, never a recompute)
                    coord.await_failover(rep.rid)
                    continue
                return resp
            return {"ok": False, "retryable": True,
                    "error": "request kept losing its replica; retry",
                    "retry_after_s": 1.0}
        return _malformed(coord, f"unknown op {kind!r}")
    except (ServeError, TimeoutError, KeyError, TypeError,
            ValueError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}
    # netrep: allow(exception-taxonomy) — wire boundary, same contract as server.dispatch_op: one failed op becomes that client's error line, the coordinator keeps serving
    except Exception as e:
        return {"ok": False,
                "error": f"internal error: {type(e).__name__}: {e}"}


def fleet_daemon(args) -> int:
    """CLI entry for ``python -m netrep_tpu serve --fleet N --socket
    PATH``: spawn N replica daemons, run the coordinator on the main
    socket, respawn failed replicas (fresh journal generation — the
    peer already adopted the old one) unless ``--no-respawn``."""
    import signal
    import sys

    if not args.socket:
        print("serve --fleet needs --socket PATH (the coordinator "
              "socket)", file=sys.stderr)
        return 2
    if args.no_journal:
        print("serve --fleet requires journaling (the failover story "
              "IS the journal); drop --no-journal", file=sys.stderr)
        return 2
    fleet_dir = args.fleet_dir or (args.socket + ".fleet")
    os.makedirs(os.path.join(fleet_dir, "ckpt"), exist_ok=True)
    os.makedirs(os.path.join(fleet_dir, "ship"), exist_ok=True)

    # the injected fault plan (drills) reaches EXACTLY ONE replica: the
    # coordinator and the other replicas must run clean
    plan = os.environ.get("NETREP_FAULT_PLAN")
    plan_replica = os.environ.get("NETREP_FLEET_FAULT_REPLICA")
    replicas = []
    for i in range(int(args.fleet)):
        extra = {}
        if plan and plan_replica is not None and str(i) == plan_replica:
            extra["NETREP_FAULT_PLAN"] = plan
        replicas.append(spawn_replica_daemon(f"r{i}", fleet_dir, args,
                                             env_extra=extra))
    for rep in replicas:
        if not _wait_socket(rep):
            print(f"fleet replica {rep.rid} never opened its socket",
                  file=sys.stderr)
            for r in replicas:
                r.close(drain=False, timeout=5)
            return 1

    coord = FleetCoordinator(replicas, FleetConfig(
        heartbeat_s=args.heartbeat_s,
        ship_interval_s=args.ship_interval_s,
        fleet_dir=fleet_dir,
        telemetry=args.telemetry,
        brownout_enter_s=args.fleet_brownout_enter_s,
        rate_pps=args.brownout_rate,
        drain_timeout_s=args.drain_timeout,
    ))
    generations = {rep.rid: 0 for rep in replicas}

    if not args.no_respawn:
        def respawn(rid, _peer):
            base = rid.split(".", 1)[0]
            generations[base] = generations.get(base, 0) + 1
            fresh = spawn_replica_daemon(
                f"{base}.g{generations[base]}",   # r0 -> r0.g1, r0.g2 ...
                fleet_dir, args, generation=generations[base],
            )
            if _wait_socket(fresh, budget_s=120.0):
                coord.join(fresh)
            else:
                logger.warning("fleet respawn of %s never came up", rid)

        coord.on_failover = respawn

    if getattr(args, "autoscale", False):
        def spawn_daemon(index: int):
            rid = f"r{index}"
            generations.setdefault(rid, 0)
            fresh = spawn_replica_daemon(rid, fleet_dir, args)
            if not _wait_socket(fresh, budget_s=120.0):
                fresh.close(drain=False, timeout=5)
                raise ServeError(
                    f"autoscale spawn of {rid} never opened its socket")
            return fresh

        Autoscaler(coord, spawn_daemon, AutoscaleConfig(
            scale_up_drain_s=float(
                getattr(args, "scale_up_drain_s", 10.0) or 10.0),
            scale_down_idle_s=float(
                getattr(args, "scale_down_idle_s", 30.0) or 30.0),
            min_replicas=int(getattr(args, "autoscale_min", 0) or 0),
            max_replicas=int(getattr(args, "autoscale_max", 0)
                             or max(4, int(args.fleet))),
        ))

    stop = threading.Event()

    # eviction drill (ISSUE 19): NETREP_FLEET_EVICT=rid[:grace[:after]]
    # fires ONE noticed eviction against the live fleet — the drill
    # thread is loud-never-fatal, the daemon keeps serving either way
    evict_spec = os.environ.get("NETREP_FLEET_EVICT")
    if evict_spec:
        def _evict_drill():
            try:
                parts = evict_spec.split(":")
                rid = parts[0]
                grace = (float(parts[1])
                         if len(parts) > 1 and parts[1] else 30.0)
                after = (float(parts[2])
                         if len(parts) > 2 and parts[2] else 1.0)
            except ValueError:
                logger.warning("bad NETREP_FLEET_EVICT spec %r",
                               evict_spec)
                return
            if stop.wait(after):
                return
            try:
                coord.evict_notice(rid, grace_s=grace)
            except (ServeError, OSError):
                logger.warning("eviction drill on %s failed", rid,
                               exc_info=True)

        threading.Thread(target=_evict_drill,
                         name="netrep-fleet-evict-drill",
                         daemon=True).start()

    def _drain_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _drain_signal)
    signal.signal(signal.SIGINT, _drain_signal)

    from .server import read_op_line

    path = args.socket
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    listener = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    listener.bind(path)
    listener.listen(64)
    listener.settimeout(0.25)
    print(json.dumps({
        "serve": "ready", "fleet": int(args.fleet), "socket": path,
        "pid": os.getpid(), "fleet_dir": fleet_dir,
        "replicas": {r.rid: r.socket_path for r in replicas},
    }), flush=True)

    def handle(conn):
        with conn:
            rfile = conn.makefile("r", encoding="utf-8")
            while True:
                op, resp = read_op_line(rfile, coord)
                if op is None and resp is None:
                    return
                if resp is not None and resp.get("empty"):
                    continue
                if resp is None:
                    resp = dispatch_fleet_op(coord, op, stop,
                                             route_mode=args.fleet_route)
                try:
                    conn.sendall(
                        (json.dumps(resp) + "\n").encode("utf-8"))
                except OSError:
                    return
                if stop.is_set():
                    return

    try:
        while not stop.is_set():
            try:
                conn, _ = listener.accept()
            except _socket.timeout:
                continue
            threading.Thread(target=handle, args=(conn,),
                             daemon=True).start()
    finally:
        listener.close()
        try:
            os.unlink(path)
        except OSError:
            pass

    coord.close(drain=True)
    print(json.dumps({"serve": "fleet_drained",
                      "replicas": sorted(generations)}), flush=True)
    return 0
