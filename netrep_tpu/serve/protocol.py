"""Wire protocol of the `netrep serve` daemon (ISSUE 7).

One JSON object per line in both directions; no HTTP framework. Requests
carry an ``op`` discriminator; responses always carry ``ok`` (and
``error`` when false). Arrays travel as nested lists — the client
re-materializes the result keys in :data:`ARRAY_KEYS` as numpy.

Ops (see :func:`netrep_tpu.serve.server.dispatch_op` for the executable
definition)::

    ping               liveness
    register_fixture   server-side deterministic fixture registration
                       (tenant, prefix, genes, modules, n_samples, seed)
    register           dataset registration with inline matrices
                       (tenant, name, correlation, network, data?,
                        assignments?) — or the DATA-ONLY atlas payload
                       (tenant, name, data, beta, assignments?): no
                       matrices, the soft-threshold spec ``beta`` (β or
                       [β, kind]) derives them on device, and the
                       returned content_digest covers the derivation
                       params so different derivations of the same data
                       never share a pack (ISSUE 9)
    analyze            blocking preservation request (tenant, discovery,
                       test | [tests...], modules?, n_perm?, seed,
                       alternative?, adaptive?, deadline_s?, timeout?,
                       idempotency_key?) — the idempotency key (ISSUE
                       10) is the request's durable identity: a
                       duplicate submission attaches to the in-flight
                       run or is answered from the journaled result,
                       never recomputed; ``deadline_s`` is ENFORCED
                       (expired requests are cancelled at pack
                       boundaries with ``request_expired``)
    metrics            Prometheus text exposition (the /metrics surface)
    stats              queue/pool/tenant counters as JSON
    adopt_journal      fleet failover (ISSUE 14): replay a dead peer's
                       shipped journal copy into this live replica —
                       datasets re-register, completed results answer
                       duplicates, unfinished requests re-queue
                       (``datasets_only: true`` replays registrations
                       alone — how a freshly autoscaled replica is
                       seeded before it enters the ring, ISSUE 19)
    shutdown           initiate the graceful drain (same path as SIGTERM)
    evict_notice       noticed preemption (ISSUE 19). On a replica:
                       begin the bounded drain now (``grace_s``). On a
                       FLEET socket: ``{"replica": "r1", "grace_s": 30}``
                       runs the full handoff — ring removal first, then
                       drain, journal-tail ship, and peer adoption — so
                       the eviction loses zero work and recomputes
                       nothing; the reply carries the handoff receipt

Fleet responses (ISSUE 14): a coordinator under ``--fleet-route
redirect`` may answer an ``analyze`` with ``{"ok": false, "retryable":
true, "redirect": "<replica socket>"}`` — the client re-sends the SAME
op (same idempotency key, same trace id) to the named socket
immediately; ``retry_after_s`` keeps its usual back-off meaning.
"""

from __future__ import annotations

import re
import uuid

import numpy as np

#: trace-context wire shape (ISSUE 13, W3C-trace-context style): every
#: ``analyze`` op may carry ``trace_ctx = {"trace": <32-hex trace id>,
#: "parent": <caller span id | None>}``. The client mints one per LOGICAL
#: request (stable across retries, like the idempotency key) unless the
#: caller supplies its own; the server journals it with the ``accepted``
#: record so a ``--recover`` boot resumes the SAME trace, and stamps it
#: on the request's telemetry span — ``utils/trace.py`` then groups the
#: request's whole span subtree (across processes and restarts) under
#: this one id.
TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,64}$")


def mint_trace_ctx(parent_span: str | None = None) -> dict:
    """A fresh client-side trace context: a 32-hex trace id (W3C trace-id
    sized) plus the caller's parent span id, if it has one."""
    return {"trace": uuid.uuid4().hex, "parent": parent_span}


def normalize_trace_ctx(ctx) -> dict | None:
    """Validate/coerce a caller-supplied trace context; returns the
    canonical ``{"trace", "parent"}`` dict or None for anything
    unusable (a malformed context must never fail the request — tracing
    only observes; the server then mints its own)."""
    if not isinstance(ctx, dict):
        return None
    trace = ctx.get("trace")
    if not (isinstance(trace, str) and TRACE_ID_RE.match(trace)):
        return None
    parent = ctx.get("parent")
    if not (parent is None or isinstance(parent, str)):
        parent = None
    return {"trace": trace, "parent": parent}


#: result keys the wire protocol round-trips as arrays
ARRAY_KEYS = (
    "observed", "p_values", "counts_hi", "counts_lo", "counts_eff",
    "n_perm_used", "n_vars_present", "prop_vars_present", "total_size",
)


def encode_arrays(obj):
    """JSON-serializable deep copy: numpy arrays → nested lists, numpy
    scalars → Python scalars."""
    if isinstance(obj, dict):
        return {k: encode_arrays(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_arrays(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    return obj


def decode_arrays(obj):
    """Inverse of :func:`encode_arrays` for result payloads: the
    :data:`ARRAY_KEYS` fields (including inside nested payloads — the
    wire response wraps the result one level down, and multi-test
    results carry per-test sub-results) come back as numpy arrays."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if k in ARRAY_KEYS and v is not None:
                out[k] = np.asarray(v)
            elif k == "tests" and isinstance(v, list):
                out[k] = [decode_arrays(t) for t in v]
            elif isinstance(v, dict):
                out[k] = decode_arrays(v)
            else:
                out[k] = v
        return out
    return obj
