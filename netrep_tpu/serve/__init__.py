"""`netrep serve` — always-on multi-tenant preservation service (ISSUE 7).

Turns the batch library into a request/response workload: tenants
register datasets once, then submit many preservation analyses; the
scheduler packs concurrent requests into shared module-size-bucket
dispatches on warm pooled engines, with admission control, weighted
round-robin fairness, SLO retirement, fault isolation, and a full
telemetry/Prometheus ops surface. Served results are bit-identical to
stand-alone ``module_preservation()`` calls with the same seed.

Surface::

    from netrep_tpu.serve import (
        PreservationServer, ServeConfig, InProcessClient,
    )

Daemon: ``python -m netrep_tpu serve --socket /tmp/netrep.sock``.
Fleet (ISSUE 14): ``serve --fleet N`` — N replica daemons behind a
coordinator with consistent-hash routing, journal shipping, replica-kill
failover, and fleet-wide admission (:mod:`netrep_tpu.serve.fleet`).
Autoscaling (ISSUE 19): ``--autoscale`` adds the closed loop — an
explicit replica lifecycle state machine
(:mod:`netrep_tpu.serve.lifecycle`), backlog-driven scale-up,
idle-driven drain-and-retire, scale-to-zero with spawn-on-demand, and
first-class eviction notices that hand off instead of failing over.
"""

from .client import InProcessClient, ServeRejected, SocketClient, retry_delay
from .fleet import (
    AutoscaleConfig, Autoscaler, FleetConfig, FleetCoordinator, HashRing,
    InProcessReplica, ReplicaLost, build_inprocess_fleet, inprocess_spawner,
)
from .journal import JournalShipper, RequestJournal
from .lifecycle import IllegalTransition, ReplicaLifecycle
from .packer import PackedEngine, PackMonitor, RequestPlan, run_pack
from .pool import ProgramPool
from .scheduler import (
    PreservationServer, QueueFull, Request, ServeConfig, ServeError,
)

__all__ = [
    "PreservationServer",
    "ServeConfig",
    "ServeError",
    "QueueFull",
    "ServeRejected",
    "ReplicaLost",
    "Request",
    "RequestJournal",
    "JournalShipper",
    "InProcessClient",
    "SocketClient",
    "ProgramPool",
    "PackedEngine",
    "PackMonitor",
    "RequestPlan",
    "run_pack",
    "retry_delay",
    "FleetConfig",
    "FleetCoordinator",
    "HashRing",
    "InProcessReplica",
    "build_inprocess_fleet",
    "inprocess_spawner",
    "Autoscaler",
    "AutoscaleConfig",
    "ReplicaLifecycle",
    "IllegalTransition",
]
