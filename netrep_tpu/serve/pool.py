"""Warm compiled-program pool (ISSUE 7).

A fresh engine instance re-traces and re-jits its chunk programs even when
an identical problem shape ran a second ago (jit caches by function
identity, and every engine builds fresh closures), so a naive service
pays the compile tax on every request. The pool keeps ENGINE INSTANCES —
device matrices, bucket structure, and their cached jitted programs —
keyed by the pack's structural signature
(:meth:`~netrep_tpu.serve.packer.RequestPlan.signature` per member plus
the dataset-pair digest and engine-config identity). Steady-state
requests with a repeated shape then hit a warm engine and pay zero
compile — the proof metric is the PR 5 ``compile_span`` event dropping to
~0 after the first same-fingerprint request (asserted by the load
generator and tests/test_serve.py).

Eviction is LRU with :meth:`~netrep_tpu.parallel.engine
.PermutationEngine.release` on the way out, so a bounded pool never
accumulates HBM: the superseded engine's device arrays are freed before
the next build allocates (the ISSUE 6 release contract).
"""

from __future__ import annotations

import collections
import threading


class ProgramPool:
    """LRU pool of warm engines. Thread-safe; builders run under the lock
    (the scheduler has one worker, so contention is registration-only)."""

    def __init__(self, max_size: int = 8):
        self.max_size = int(max_size)
        self._lru: "collections.OrderedDict[tuple, object]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._lru)

    def get(self, key, builder):
        """Return ``(engine, hit)`` — the pooled engine for ``key``, or a
        fresh ``builder()`` result (cached unless the pool is disabled
        with ``max_size=0``). Evicts least-recently-used engines above
        ``max_size``, releasing their device arrays first."""
        with self._lock:
            eng = self._lru.pop(key, None)
            hit = eng is not None
            if eng is None:
                self.misses += 1
                eng = builder()
            else:
                self.hits += 1
            if self.max_size > 0:
                self._lru[key] = eng
                while len(self._lru) > self.max_size:
                    _, old = self._lru.popitem(last=False)
                    self.evictions += 1
                    rel = getattr(old, "release", None)
                    if rel is not None:
                        rel()
            return eng, hit

    def discard(self, key) -> None:
        """Drop (and release) one pooled engine — the scheduler evicts an
        engine whose run just failed rather than reuse suspect device
        state."""
        with self._lock:
            old = self._lru.pop(key, None)
        if old is not None:
            rel = getattr(old, "release", None)
            if rel is not None:
                rel()

    def clear(self) -> None:
        """Release every pooled engine (service drain/shutdown)."""
        with self._lock:
            while self._lru:
                _, old = self._lru.popitem(last=False)
                rel = getattr(old, "release", None)
                if rel is not None:
                    rel()

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._lru),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
