"""Clients for `netrep serve` (ISSUE 7).

- :class:`InProcessClient` — wraps a live
  :class:`~netrep_tpu.serve.scheduler.PreservationServer` directly: zero
  transport, numpy in/out. This is what the tier-1 tests and the load
  generator drive (the serve test surface is CPU-only and socket-free by
  design).
- :class:`SocketClient` — line-delimited JSON over the daemon's unix
  socket (:mod:`netrep_tpu.serve.server`); arrays travel as nested
  lists, responses come back with arrays re-materialized as numpy.

Retry-with-backoff (ISSUE 10): both clients' ``analyze`` take
``retries=N``. Every attempt of one logical request carries the SAME
idempotency key (auto-generated when the caller passes none), so a retry
after a ``QueueFull``/brownout rejection, a dropped connection, or a
server restart can never recompute or double-run: the server attaches
the retry to the in-flight request or answers from the journaled result.
Backoff is exponential with DETERMINISTIC jitter — the
:mod:`netrep_tpu.utils.faults` convention: the jitter factor hashes
``(key, attempt)``, so a rerun of the same client schedule sleeps the
same delays. A server-supplied ``retry_after_s`` hint (the brownout
drain estimate) takes precedence over the computed delay.
"""

from __future__ import annotations

import hashlib
import json
import socket
import time
import uuid

from .protocol import decode_arrays, encode_arrays, mint_trace_ctx


class ServeRejected(RuntimeError):
    """The daemon refused the request with a retryable rejection
    (``QueueFull``/brownout): back off — ``retry_after_s`` is the
    server's drain-time hint when it has one. ``redirect`` (ISSUE 14
    fleet coordinator, ``--fleet-route redirect``) names another socket
    the client should re-send the SAME op to — a routing hint, not an
    overload signal, so the retry is immediate."""

    def __init__(self, msg: str, retry_after_s: float | None = None,
                 redirect: str | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.redirect = redirect


def retry_delay(attempt: int, token: str, base_s: float = 0.25,
                factor: float = 2.0, max_s: float = 10.0,
                jitter: float = 0.25) -> float:
    """Exponential backoff with deterministic jitter, per the
    ``utils/faults.py`` convention: the jitter hashes ``(token,
    attempt)`` so identical retry schedules sleep identically (no hidden
    RNG state — reproducible load-generator traces)."""
    d = min(max_s, base_s * factor ** (max(1, attempt) - 1))
    if jitter:
        h = int.from_bytes(
            hashlib.blake2b(f"{token}:{attempt}".encode(),
                            digest_size=8).digest(),
            "big",
        )
        d *= 1.0 + jitter * (h / float(2 ** 64) * 2.0 - 1.0)
    return max(0.0, d)


class InProcessClient:
    """Direct (same-process) client — the canonical programmatic surface::

        from netrep_tpu.serve import PreservationServer, InProcessClient
        client = InProcessClient(PreservationServer())
        client.register_dataset("acme", "d", network=..., correlation=...,
                                data=..., assignments=labels)
        client.register_dataset("acme", "t", network=..., correlation=...,
                                data=...)
        res = client.analyze("acme", "d", "t", n_perm=2000, seed=1)
        res["p_values"]   # bit-identical to module_preservation(...)
    """

    def __init__(self, server):
        self.server = server

    def register_tenant(self, name: str, weight: int = 1) -> None:
        self.server.register_tenant(name, weight)

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        return self.server.register_dataset(tenant, name, **kw)

    def register_fixture(self, tenant: str, prefix: str = "fx", **kw) -> dict:
        return self.server.register_fixture(tenant, prefix, **kw)

    def submit(self, tenant: str, discovery: str, test, **kw):
        """Non-blocking submit; returns the request handle for
        :meth:`result`. Mints a trace context (ISSUE 13) unless the
        caller supplies its own — the id the request's whole span
        subtree carries, across processes and server restarts."""
        kw.setdefault("trace_ctx", mint_trace_ctx())
        return self.server.submit(tenant, discovery, test, **kw)

    def result(self, handle, timeout: float | None = None) -> dict:
        return self.server.wait(handle, timeout=timeout)

    def analyze(self, tenant: str, discovery: str, test, *,
                timeout: float | None = None, retries: int = 0,
                retry_base_s: float = 0.25, sleep=time.sleep,
                **kw) -> dict:
        """Blocking submit + wait. With ``retries`` > 0, an admission
        rejection (``QueueFull``, incl. brownout shedding) is retried
        with deterministic backoff under ONE idempotency key — the
        server's ``retry_after_s`` hint, when present, wins over the
        computed delay. Safe by construction: the key dedups every
        attempt onto one computation. The trace context (ISSUE 13), like
        the idempotency key, is minted ONCE per logical request — every
        retry carries the same trace id."""
        from .scheduler import QueueFull

        key = kw.setdefault("idempotency_key", f"c-{uuid.uuid4().hex}")
        kw.setdefault("trace_ctx", mint_trace_ctx())
        attempt = 0
        while True:
            try:
                return self.server.analyze(tenant, discovery, test,
                                           timeout=timeout, **kw)
            except QueueFull as e:
                attempt += 1
                if attempt > retries:
                    raise
                delay = retry_delay(attempt, key, base_s=retry_base_s)
                if e.retry_after_s is not None:
                    delay = max(delay, float(e.retry_after_s))
                sleep(delay)

    def metrics(self) -> str:
        return self.server.metrics_text()

    def stats(self) -> dict:
        return self.server.stats()


class SocketClient:
    """Line-delimited JSON client for the unix-socket daemon
    (``python -m netrep_tpu serve --socket PATH``)."""

    def __init__(self, path: str, timeout: float = 120.0):
        self.path = path
        self._timeout = timeout
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(self._timeout)
        self._sock.connect(self.path)
        self._rfile = self._sock.makefile("r", encoding="utf-8")

    def reconnect(self) -> None:
        """Drop and re-dial the socket — the retry path after the daemon
        restarted (``serve --recover``); the idempotency key makes the
        re-sent request safe."""
        try:
            self.close()
        except OSError:
            pass
        self._connect()

    def request(self, op: str, **kw) -> dict:
        payload = encode_arrays({"op": op, **kw})
        self._sock.sendall((json.dumps(payload) + "\n").encode("utf-8"))
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("serve daemon closed the connection")
        resp = json.loads(line)
        if not resp.get("ok", False):
            if resp.get("retryable") or resp.get("redirect"):
                raise ServeRejected(
                    resp.get("error", "serve daemon rejected the request"),
                    retry_after_s=resp.get("retry_after_s"),
                    redirect=resp.get("redirect"),
                )
            raise RuntimeError(resp.get("error", "serve daemon error"))
        return decode_arrays(resp)

    def ping(self) -> dict:
        return self.request("ping")

    def register_fixture(self, tenant: str, prefix: str = "fx", **kw) -> dict:
        return self.request("register_fixture", tenant=tenant,
                            prefix=prefix, **kw)["fixture"]

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        return self.request("register", tenant=tenant, name=name,
                            **kw)["digest"]

    def analyze(self, tenant: str, discovery: str, test, *,
                retries: int = 0, retry_base_s: float = 0.25,
                sleep=time.sleep, **kw) -> dict:
        """Blocking analyze over the socket. With ``retries`` > 0, a
        retryable rejection (QueueFull/brownout — honoring the server's
        ``retry_after_s`` hint) or a dropped/restarted daemon connection
        is retried under ONE idempotency key: after a ``serve --recover``
        boot — or a fleet replica failover (ISSUE 14) — the re-sent
        request is answered from the journal (or attaches to its
        re-queued run) instead of recomputing. A coordinator
        ``redirect`` hint re-points the connection at the named socket
        and re-sends IMMEDIATELY (it is routing, not overload, so it
        costs no retry attempt; hops are bounded). The trace context is
        minted once per logical request (ISSUE 13): every attempt —
        across reconnects, redirects, and daemon restarts — carries the
        same trace id, so the merged trace is one story."""
        key = kw.setdefault("idempotency_key", f"c-{uuid.uuid4().hex}")
        kw.setdefault("trace_ctx", mint_trace_ctx())
        attempt = 0
        hops = 0
        while True:
            try:
                return self.request("analyze", tenant=tenant,
                                    discovery=discovery, test=test,
                                    **kw)["result"]
            except (ServeRejected, ConnectionError, OSError) as e:
                if getattr(e, "redirect", None) and hops < 8:
                    # routing hint: follow to the named replica socket
                    # under the SAME key/trace, no backoff consumed
                    hops += 1
                    self.path = e.redirect
                    try:
                        self.reconnect()
                        continue
                    except OSError:
                        pass   # fall through to the retry ladder
                attempt += 1
                if attempt > retries:
                    raise
                delay = retry_delay(attempt, key, base_s=retry_base_s)
                if getattr(e, "retry_after_s", None) is not None:
                    delay = max(delay, float(e.retry_after_s))
                sleep(delay)
                if not isinstance(e, ServeRejected):
                    try:
                        self.reconnect()
                    except OSError:
                        continue  # daemon still down — next attempt re-dials

    def metrics(self) -> str:
        return self.request("metrics")["text"]

    def stats(self) -> dict:
        return self.request("stats")["stats"]

    def shutdown(self) -> dict:
        return self.request("shutdown")

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()
