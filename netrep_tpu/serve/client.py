"""Clients for `netrep serve` (ISSUE 7).

- :class:`InProcessClient` — wraps a live
  :class:`~netrep_tpu.serve.scheduler.PreservationServer` directly: zero
  transport, numpy in/out. This is what the tier-1 tests and the load
  generator drive (the serve test surface is CPU-only and socket-free by
  design).
- :class:`SocketClient` — line-delimited JSON over the daemon's unix
  socket (:mod:`netrep_tpu.serve.server`); arrays travel as nested
  lists, responses come back with arrays re-materialized as numpy.
"""

from __future__ import annotations

import json
import socket

from .protocol import decode_arrays, encode_arrays


class InProcessClient:
    """Direct (same-process) client — the canonical programmatic surface::

        from netrep_tpu.serve import PreservationServer, InProcessClient
        client = InProcessClient(PreservationServer())
        client.register_dataset("acme", "d", network=..., correlation=...,
                                data=..., assignments=labels)
        client.register_dataset("acme", "t", network=..., correlation=...,
                                data=...)
        res = client.analyze("acme", "d", "t", n_perm=2000, seed=1)
        res["p_values"]   # bit-identical to module_preservation(...)
    """

    def __init__(self, server):
        self.server = server

    def register_tenant(self, name: str, weight: int = 1) -> None:
        self.server.register_tenant(name, weight)

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        return self.server.register_dataset(tenant, name, **kw)

    def register_fixture(self, tenant: str, prefix: str = "fx", **kw) -> dict:
        return self.server.register_fixture(tenant, prefix, **kw)

    def submit(self, tenant: str, discovery: str, test, **kw):
        """Non-blocking submit; returns the request handle for
        :meth:`result`."""
        return self.server.submit(tenant, discovery, test, **kw)

    def result(self, handle, timeout: float | None = None) -> dict:
        return self.server.wait(handle, timeout=timeout)

    def analyze(self, tenant: str, discovery: str, test, *,
                timeout: float | None = None, **kw) -> dict:
        return self.server.analyze(tenant, discovery, test,
                                   timeout=timeout, **kw)

    def metrics(self) -> str:
        return self.server.metrics_text()

    def stats(self) -> dict:
        return self.server.stats()


class SocketClient:
    """Line-delimited JSON client for the unix-socket daemon
    (``python -m netrep_tpu serve --socket PATH``)."""

    def __init__(self, path: str, timeout: float = 120.0):
        self.path = path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(path)
        self._rfile = self._sock.makefile("r", encoding="utf-8")

    def request(self, op: str, **kw) -> dict:
        payload = encode_arrays({"op": op, **kw})
        self._sock.sendall((json.dumps(payload) + "\n").encode("utf-8"))
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("serve daemon closed the connection")
        resp = json.loads(line)
        if not resp.get("ok", False):
            raise RuntimeError(resp.get("error", "serve daemon error"))
        return decode_arrays(resp)

    def ping(self) -> dict:
        return self.request("ping")

    def register_fixture(self, tenant: str, prefix: str = "fx", **kw) -> dict:
        return self.request("register_fixture", tenant=tenant,
                            prefix=prefix, **kw)["fixture"]

    def register_dataset(self, tenant: str, name: str, **kw) -> str:
        return self.request("register", tenant=tenant, name=name,
                            **kw)["digest"]

    def analyze(self, tenant: str, discovery: str, test, **kw) -> dict:
        return self.request("analyze", tenant=tenant, discovery=discovery,
                            test=test, **kw)["result"]

    def metrics(self) -> str:
        return self.request("metrics")["text"]

    def stats(self) -> dict:
        return self.request("stats")["stats"]

    def shutdown(self) -> dict:
        return self.request("shutdown")

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()
