"""Durable write-ahead request journal for `netrep serve` (ISSUE 10).

PRs 4/6 made the *engine* survive anything short of losing the whole
machine; this module extends the same durability contract up through the
request/response layer. The serving daemon appends one fsynced JSON line
per state transition, in the crash-safe style of
:mod:`netrep_tpu.utils.telemetry` (append-only JSONL, a crash loses at
most the in-flight line, torn final lines are tolerated on read):

- ``accepted`` — written and **fsynced before the request is admitted**
  to the queue: tenant, dataset names + content digests, the full
  analyze params, the seed, the client-supplied **idempotency key**
  (auto-assigned when the client sends none), and the request's **trace
  context** (ISSUE 13: ``trace={"trace": <id>, "parent": <span>}``) — so
  a ``--recover`` boot re-queues the request under the SAME client-
  minted trace id and the pre- and post-crash span trees merge into one
  continuous trace. An accepted record with no matching terminal record
  is, by definition, work the server still owes.
- ``done`` / ``failed`` — the terminal record: the result digest and the
  full wire-encoded result (``done``), or the error string (``failed``).
  A ``done`` record is what a duplicate submission with the same
  idempotency key is answered from after a restart — no recompute.
- ``tenant`` / ``dataset`` — registrations, so ``--recover`` can rebuild
  the server's dataset references without the clients re-uploading.
  Fixture registrations journal their *parameters* (cheap, re-derivable);
  inline registrations journal the encoded matrices (the wire payload).
- ``drain_requeued`` — informational: a bounded SIGTERM drain ran out of
  time and these accepted-but-unfinished keys exit the process as
  journaled work, picked up by the next ``--recover`` boot.

Recovery (:func:`scan` + ``PreservationServer`` replay) is deterministic:
tenants and datasets re-register in journal order, completed results are
loaded into the idempotency map, and every accepted-but-not-terminal
request re-queues in original ``seq`` order — combined with the engine's
mesh-shape-independent checkpoints and the serve layer's bit-identical
packing, a ``SIGKILL`` mid-pack followed by ``serve --recover`` yields
results bit-identical to an uninterrupted server.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

logger = logging.getLogger("netrep_tpu")

#: journal line format version — every record carries it as ``jv`` (the
#: discriminator that lets a journal share parsers with telemetry JSONL)
JOURNAL_VERSION = 1

#: record kinds with a terminal meaning for an accepted request
TERMINAL_KINDS = ("done", "failed")


def _json_default(v):
    # numpy scalars/arrays ride journal records as plain JSON, same
    # tolerance rule as the telemetry sink
    if hasattr(v, "item") and not hasattr(v, "__len__"):
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return str(v)


def result_digest(result: dict) -> str:
    """Stable digest of a (wire-encoded) result payload — the ``done``
    record's cheap identity, letting the recovery drill assert
    "re-served == originally served" without diffing full arrays."""
    h = hashlib.blake2b(digest_size=8)
    h.update(json.dumps(result, sort_keys=True,
                        default=_json_default).encode())
    return h.hexdigest()


class RequestJournal:
    """Append-only fsynced journal writer.

    Unlike the telemetry sink (flush-only — losing a trailing event is
    acceptable), ``accepted`` records are the server's promise to the
    client, so every append is ``flush`` + ``os.fsync``: when ``submit``
    returns, the request survives a ``SIGKILL``. Thread-safe (the
    scheduler appends under its own lock, the transports may not).
    A dead sink (full disk, revoked path) raises — accepting work that
    cannot be journaled would silently void the durability contract.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", encoding="utf-8")

    def append(self, kind: str, **fields) -> dict:
        """Append one fsynced record; returns it."""
        rec = {"jv": JOURNAL_VERSION, "t": time.time(),
               "kind": str(kind), **fields}
        line = json.dumps(rec, default=_json_default) + "\n"
        with self._lock:
            if self._fh is None:
                raise OSError(f"journal {self.path!r} is closed")
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_records(path: str):
    """Stream the journal's records, skipping anything that is not a
    schema-matching line — in particular the torn final line a crash mid-
    append leaves behind (same tolerance as the telemetry reader)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn/corrupt line — tolerated by design
            if (isinstance(rec, dict) and rec.get("jv") == JOURNAL_VERSION
                    and isinstance(rec.get("kind"), str)):
                yield rec


def scan(path: str) -> dict:
    """Fold a journal into the recovery state the server replays:

    - ``tenants``: ``{name: weight}`` in first-seen order;
    - ``datasets``: the dataset/fixture registration records, in order;
    - ``results``: ``{idempotency_key: done record}`` — completed work a
      duplicate submission is answered from without recomputing;
    - ``failed``: ``{idempotency_key: failed record}`` — terminal, never
      re-queued (a deadline miss must not resurrect on restart);
    - ``pending``: accepted records with **no terminal record**, in
      original ``seq`` order — the work the restarted server re-queues.
    """
    tenants: dict[str, int] = {}
    datasets: list[dict] = []
    accepted: dict[str, dict] = {}
    results: dict[str, dict] = {}
    failed: dict[str, dict] = {}
    drain_requeued = 0
    for rec in read_records(path):
        kind = rec["kind"]
        if kind == "tenant":
            tenants[str(rec["tenant"])] = int(rec.get("weight", 1))
        elif kind == "dataset":
            tenants.setdefault(str(rec["tenant"]), 1)
            datasets.append(rec)
        elif kind == "accepted":
            key = str(rec.get("key"))
            accepted[key] = rec
        elif kind == "done":
            results[str(rec.get("key"))] = rec
        elif kind == "failed":
            failed[str(rec.get("key"))] = rec
        elif kind == "drain_requeued":
            drain_requeued += len(rec.get("keys", []))
    pending = sorted(
        (rec for key, rec in accepted.items()
         if key not in results and key not in failed),
        key=lambda r: int(r.get("seq", 0)),
    )
    return {
        "tenants": tenants,
        "datasets": datasets,
        "accepted": accepted,
        "results": results,
        "failed": failed,
        "pending": pending,
        "n_accepted": len(accepted),
        "n_drain_requeued": drain_requeued,
    }


class JournalShipper:
    """Continuous journal replication to a designated peer (ISSUE 14
    ``serve --fleet``): a daemon thread tails the source journal and
    appends every newly-fsynced COMPLETE line to the peer's copy
    (``dest_path``), fsyncing the copy before advancing the acked
    offset — so the shipped copy is itself a valid journal a failover
    replays with the ordinary ``scan()``/recovery machinery.

    Protocol details the fleet contract depends on:

    - **segment tailing with acked offsets**: each pass reads from the
      last acked byte offset to EOF and ships only up to the last
      newline — a torn in-flight line (the crash signature the reader
      already tolerates) is left for the next pass, so the copy never
      contains a record the source had not durably finished;
    - **ack = fsynced at the peer**: the offset only advances after the
      copy's ``fsync`` returns, and it is persisted to a sidecar
      (``<dest>.offset``) so shipping resumes — never re-ships, never
      skips — across a shipper (or coordinator) restart;
    - **telemetry**: each pass that moves data emits ``journal_shipped``
      (replica, records, bytes, offset) on the coordinator's bus — the
      per-replica section of ``telemetry``/``top`` folds these.

    A missing source file (replica not booted yet) is simply "nothing to
    ship". The thread is owned by the fleet coordinator; ``flush()`` is
    the synchronous one-pass entry the failover path (and tests) call
    directly."""

    def __init__(self, src_path: str, dest_path: str, *,
                 interval_s: float = 0.2, replica: str | None = None,
                 telemetry=None):
        self.src_path = os.fspath(src_path)
        self.dest_path = os.fspath(dest_path)
        self.interval_s = float(interval_s)
        self.replica = replica
        self.tel = telemetry
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        d = os.path.dirname(os.path.abspath(self.dest_path))
        os.makedirs(d, exist_ok=True)
        self._offset_path = self.dest_path + ".offset"
        self._offset = self._load_offset()

    def _load_offset(self) -> int:
        try:
            with open(self._offset_path, encoding="utf-8") as f:
                return max(0, int(f.read().strip() or 0))
        except (OSError, ValueError):
            return 0

    @property
    def acked_offset(self) -> int:
        with self._lock:
            return self._offset

    def flush(self) -> int:
        """One synchronous ship pass; returns the bytes moved. Reads the
        source from the acked offset, ships complete lines only, fsyncs
        the copy, then persists the new offset (crash between fsync and
        offset write re-ships — ``scan()`` folds duplicate records to the
        same state, so re-shipping is safe; skipping would not be)."""
        with self._lock:
            return self._ship_locked()

    def _ship_locked(self) -> int:
        try:
            with open(self.src_path, "rb") as src:
                src.seek(self._offset)
                chunk = src.read()
        except OSError:
            return 0      # source not there yet / unreadable: next pass
        if not chunk:
            return 0
        cut = chunk.rfind(b"\n")
        if cut < 0:
            return 0      # only a torn in-flight line so far
        chunk = chunk[: cut + 1]
        try:
            with open(self.dest_path, "ab") as dst:
                dst.write(chunk)
                dst.flush()
                os.fsync(dst.fileno())
            self._offset += len(chunk)
            with open(self._offset_path, "w", encoding="utf-8") as f:
                f.write(str(self._offset))
        except OSError as e:
            logger.warning("journal shipper %s -> %s failed: %s",
                           self.src_path, self.dest_path, e)
            return 0
        if self.tel is not None:
            self.tel.emit(
                "journal_shipped", replica=self.replica,
                records=chunk.count(b"\n"), bytes=len(chunk),
                offset=self._offset,
            )
        return len(chunk)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._lock:
                self._ship_locked()

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name="netrep-journal-shipper",
                daemon=True,
            )
            self._thread.start()

    def stop(self, final_flush: bool = True) -> None:
        """Stop the tailing thread (joined), optionally running one last
        ship pass so everything fsynced at the source is on the copy."""
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        if final_flush:
            self.flush()


def pack_checkpoint_path(ckpt_dir: str, cfg_id: str, members) -> str:
    """Deterministic per-pack checkpoint path: a digest of the member
    requests' durable identities (journal key, seed, n_perm, plan
    signature) plus the engine-config identity. The same requests
    re-queued by ``--recover`` re-form the same pack and find the same
    checkpoint; any other composition hashes elsewhere and simply
    recomputes (recovery parity never depends on the resume firing — the
    checkpoint is a work-saving optimization, bit-identical either way)."""
    h = hashlib.blake2b(digest_size=12)
    h.update(cfg_id.encode())
    for key, seed, n_perm, sig in sorted(members):
        h.update(f"|{key}:{seed}:{n_perm}:{sig}".encode())
    return os.path.join(ckpt_dir, f"pack_{h.hexdigest()}.npz")
