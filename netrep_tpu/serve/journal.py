"""Durable write-ahead request journal for `netrep serve` (ISSUE 10).

PRs 4/6 made the *engine* survive anything short of losing the whole
machine; this module extends the same durability contract up through the
request/response layer. The serving daemon appends one fsynced JSON line
per state transition, in the crash-safe style of
:mod:`netrep_tpu.utils.telemetry` (append-only JSONL, a crash loses at
most the in-flight line, torn final lines are tolerated on read):

- ``accepted`` — written and **fsynced before the request is admitted**
  to the queue: tenant, dataset names + content digests, the full
  analyze params, the seed, the client-supplied **idempotency key**
  (auto-assigned when the client sends none), and the request's **trace
  context** (ISSUE 13: ``trace={"trace": <id>, "parent": <span>}``) — so
  a ``--recover`` boot re-queues the request under the SAME client-
  minted trace id and the pre- and post-crash span trees merge into one
  continuous trace. An accepted record with no matching terminal record
  is, by definition, work the server still owes.
- ``done`` / ``failed`` — the terminal record: the result digest and the
  full wire-encoded result (``done``), or the error string (``failed``).
  A ``done`` record is what a duplicate submission with the same
  idempotency key is answered from after a restart — no recompute.
- ``tenant`` / ``dataset`` — registrations, so ``--recover`` can rebuild
  the server's dataset references without the clients re-uploading.
  Fixture registrations journal their *parameters* (cheap, re-derivable);
  inline registrations journal the encoded matrices (the wire payload).
- ``drain_requeued`` — informational: a bounded SIGTERM drain ran out of
  time and these accepted-but-unfinished keys exit the process as
  journaled work, picked up by the next ``--recover`` boot.

Recovery (:func:`scan` + ``PreservationServer`` replay) is deterministic:
tenants and datasets re-register in journal order, completed results are
loaded into the idempotency map, and every accepted-but-not-terminal
request re-queues in original ``seq`` order — combined with the engine's
mesh-shape-independent checkpoints and the serve layer's bit-identical
packing, a ``SIGKILL`` mid-pack followed by ``serve --recover`` yields
results bit-identical to an uninterrupted server.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

logger = logging.getLogger("netrep_tpu")

#: journal line format version — every record carries it as ``jv`` (the
#: discriminator that lets a journal share parsers with telemetry JSONL)
JOURNAL_VERSION = 1

#: record kinds with a terminal meaning for an accepted request
TERMINAL_KINDS = ("done", "failed")


def _json_default(v):
    # numpy scalars/arrays ride journal records as plain JSON, same
    # tolerance rule as the telemetry sink
    if hasattr(v, "item") and not hasattr(v, "__len__"):
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return str(v)


def result_digest(result: dict) -> str:
    """Stable digest of a (wire-encoded) result payload — the ``done``
    record's cheap identity, letting the recovery drill assert
    "re-served == originally served" without diffing full arrays."""
    h = hashlib.blake2b(digest_size=8)
    h.update(json.dumps(result, sort_keys=True,
                        default=_json_default).encode())
    return h.hexdigest()


class RequestJournal:
    """Append-only fsynced journal writer.

    Unlike the telemetry sink (flush-only — losing a trailing event is
    acceptable), ``accepted`` records are the server's promise to the
    client, so every append is ``flush`` + ``os.fsync``: when ``submit``
    returns, the request survives a ``SIGKILL``. Thread-safe (the
    scheduler appends under its own lock, the transports may not).
    A dead sink (full disk, revoked path) raises — accepting work that
    cannot be journaled would silently void the durability contract.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = os.fspath(path)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", encoding="utf-8")

    def append(self, kind: str, **fields) -> dict:
        """Append one fsynced record; returns it."""
        rec = {"jv": JOURNAL_VERSION, "t": time.time(),
               "kind": str(kind), **fields}
        line = json.dumps(rec, default=_json_default) + "\n"
        with self._lock:
            if self._fh is None:
                raise OSError(f"journal {self.path!r} is closed")
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_records(path: str):
    """Stream the journal's records, skipping anything that is not a
    schema-matching line — in particular the torn final line a crash mid-
    append leaves behind (same tolerance as the telemetry reader)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn/corrupt line — tolerated by design
            if (isinstance(rec, dict) and rec.get("jv") == JOURNAL_VERSION
                    and isinstance(rec.get("kind"), str)):
                yield rec


def scan(path: str) -> dict:
    """Fold a journal into the recovery state the server replays:

    - ``tenants``: ``{name: weight}`` in first-seen order;
    - ``datasets``: the dataset/fixture registration records, in order;
    - ``results``: ``{idempotency_key: done record}`` — completed work a
      duplicate submission is answered from without recomputing;
    - ``failed``: ``{idempotency_key: failed record}`` — terminal, never
      re-queued (a deadline miss must not resurrect on restart);
    - ``pending``: accepted records with **no terminal record**, in
      original ``seq`` order — the work the restarted server re-queues.
    """
    tenants: dict[str, int] = {}
    datasets: list[dict] = []
    accepted: dict[str, dict] = {}
    results: dict[str, dict] = {}
    failed: dict[str, dict] = {}
    drain_requeued = 0
    for rec in read_records(path):
        kind = rec["kind"]
        if kind == "tenant":
            tenants[str(rec["tenant"])] = int(rec.get("weight", 1))
        elif kind == "dataset":
            tenants.setdefault(str(rec["tenant"]), 1)
            datasets.append(rec)
        elif kind == "accepted":
            key = str(rec.get("key"))
            accepted[key] = rec
        elif kind == "done":
            results[str(rec.get("key"))] = rec
        elif kind == "failed":
            failed[str(rec.get("key"))] = rec
        elif kind == "drain_requeued":
            drain_requeued += len(rec.get("keys", []))
    pending = sorted(
        (rec for key, rec in accepted.items()
         if key not in results and key not in failed),
        key=lambda r: int(r.get("seq", 0)),
    )
    return {
        "tenants": tenants,
        "datasets": datasets,
        "accepted": accepted,
        "results": results,
        "failed": failed,
        "pending": pending,
        "n_accepted": len(accepted),
        "n_drain_requeued": drain_requeued,
    }


def pack_checkpoint_path(ckpt_dir: str, cfg_id: str, members) -> str:
    """Deterministic per-pack checkpoint path: a digest of the member
    requests' durable identities (journal key, seed, n_perm, plan
    signature) plus the engine-config identity. The same requests
    re-queued by ``--recover`` re-form the same pack and find the same
    checkpoint; any other composition hashes elsewhere and simply
    recomputes (recovery parity never depends on the resume firing — the
    checkpoint is a work-saving optimization, bit-identical either way)."""
    h = hashlib.blake2b(digest_size=12)
    h.update(cfg_id.encode())
    for key, seed, n_perm, sig in sorted(members):
        h.update(f"|{key}:{seed}:{n_perm}:{sig}".encode())
    return os.path.join(ckpt_dir, f"pack_{h.hexdigest()}.npz")
