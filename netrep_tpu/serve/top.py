"""`python -m netrep_tpu top` — live ops dashboard for the serve plane
(ISSUE 13).

A refresh-loop view over the daemon's existing ``stats``/``metrics`` ops
(no new wire surface): per-tenant queue depth, p50/p99 latency from the
pinned-bucket histograms, attributed device-seconds (total and per
wall-second), SLO burn rate, and the server-level brownout/inflight/pack
state. ``--once`` prints a single frame; ``--json`` emits the snapshot
as one machine-readable line (scripts, CI, the ``tpu_watch.sh`` serve
drill artifact). The renderer is shared with ``telemetry --follow`` —
the same tenant table drawn from a live socket here is drawn from the
event stream there, so the two views can never diverge in shape.

Everything here is derived from ``PreservationServer.stats()`` alone, so
the tier-1 test drives :func:`snapshot` against an in-process server
without a socket.
"""

from __future__ import annotations

import json
import sys
import time

#: tenant-table columns: (header, width, stats-row key, format)
_COLUMNS = (
    ("tenant", 10, "tenant", "s"),
    ("q", 4, "queue_depth", "d"),
    ("done", 6, "done", "d"),
    ("fail", 5, "failed", "d"),
    ("exp", 4, "expired", "d"),
    ("p50_ms", 8, "p50_ms", ".1f"),
    ("p99_ms", 8, "p99_ms", ".1f"),
    ("dev_s", 8, "device_s", ".3f"),
    ("dev_s/s", 8, "device_s_per_s", ".4f"),
    ("burn", 6, "burn_rate", ".2f"),
)


def snapshot(stats: dict) -> dict:
    """Shape the server's ``stats()`` dict into the dashboard snapshot:
    one row per tenant plus the server-level header fields. This is the
    ``--json`` payload and the tier-1 test surface."""
    rows = []
    for name in sorted(stats.get("tenants", {})):
        t = stats["tenants"][name]
        cost = t.get("cost") or {}
        p50 = t.get("p50_s")
        p99 = t.get("p99_s")
        rows.append({
            "tenant": name,
            "queue_depth": int(t.get("queue_depth", 0)),
            "done": int(t.get("done", 0)),
            "failed": int(t.get("failed", 0)),
            "expired": int(t.get("expired", 0)),
            "deduped": int(t.get("deduped", 0)),
            "p50_ms": 1000.0 * p50 if p50 is not None else None,
            "p99_ms": 1000.0 * p99 if p99 is not None else None,
            "device_s": float(cost.get("device_s", 0.0)),
            "device_s_per_s": float(t.get("device_s_per_s", 0.0)),
            "perms": int(cost.get("perms", 0)),
            "bytes_to_host": int(cost.get("bytes_to_host", 0)),
            "burn_rate": float(t.get("burn_rate", 0.0)),
        })
    snap = {
        "tenants": rows,
        "brownout": bool(stats.get("brownout", False)),
        "accepting": bool(stats.get("accepting", True)),
        "inflight": int(stats.get("inflight", 0)),
        "packs": int(stats.get("packs", 0)),
        "uptime_s": float(stats.get("uptime_s", 0.0)),
        "slo_s": stats.get("slo_s"),
        "slo_budget": stats.get("slo_budget"),
    }
    # fleet coordinator stats (ISSUE 14): one row per replica — alive,
    # queue/backlog, rate, packs — rendered as its own table section
    if stats.get("replicas"):
        snap["fleet"] = True
        snap["replicas"] = [
            {
                "replica": rid,
                "alive": bool(r.get("alive", False)),
                # lifecycle state machine (ISSUE 19): the pinned
                # spawning/ready/draining/dead state + respawn
                # generation, straight from the coordinator's rows
                "state": r.get("state"),
                "gen": int(r.get("gen", 0) or 0),
                "idle_s": r.get("idle_s"),
                "queue_depth": int(r.get("queue_depth", 0) or 0),
                "backlog_perms": int(r.get("backlog_perms", 0) or 0),
                "rate_pps": r.get("rate_pps"),
                "utilisation": r.get("utilisation"),
                "packs": int(r.get("packs", 0) or 0),
                "done": int(r.get("done", 0) or 0),
                "brownout": bool(r.get("brownout", False)),
            }
            for rid, r in sorted(stats["replicas"].items())
        ]
    return snap


def render_tenant_table(rows: list[dict]) -> str:
    """The shared tenant table (``top`` and ``telemetry --follow``): one
    row per tenant over the :data:`_COLUMNS` schema; missing quantiles
    (no completed requests yet) render as ``-``."""
    out = []
    out.append("  ".join(
        f"{h:>{w}}" if fmt != "s" else f"{h:<{w}}"
        for h, w, _k, fmt in _COLUMNS
    ))
    for r in rows:
        cells = []
        for _h, w, k, fmt in _COLUMNS:
            v = r.get(k)
            if fmt == "s":
                cells.append(f"{str(v):<{w}}")
            elif v is None:
                cells.append(f"{'-':>{w}}")
            else:
                cells.append(f"{v:>{w}{fmt}}")
        out.append("  ".join(cells))
    return "\n".join(out)


#: per-replica table columns (fleet dashboards, ISSUE 14)
_REPLICA_COLUMNS = (
    ("replica", 10, "replica", "s"),
    ("up", 4, "up", "s"),
    # lifecycle columns (ISSUE 19): the state machine's word for the
    # replica (spawning/ready/draining/dead) + its respawn generation
    ("state", 9, "state", "s"),
    ("gen", 4, "gen", "d"),
    ("q", 4, "queue_depth", "d"),
    ("backlog", 8, "backlog_perms", "d"),
    ("rate/s", 9, "rate_pps", ".1f"),
    # roofline gauge (ISSUE 18): achieved fraction of speed of light
    # from the replica's last engine run — `-` until one has run or on
    # device kinds without a peak-table entry (null, never a guess)
    ("util", 5, "utilisation", ".2f"),
    ("packs", 6, "packs", "d"),
    ("done", 6, "done", "d"),
)


def render_replica_table(rows: list[dict]) -> str:
    """The fleet's per-replica section: one row per replica over the
    :data:`_REPLICA_COLUMNS` schema (``up`` collapses alive/brownout
    into ``yes``/``brn``/``DEAD``)."""
    out = []
    out.append("  ".join(
        f"{h:>{w}}" if fmt != "s" else f"{h:<{w}}"
        for h, w, _k, fmt in _REPLICA_COLUMNS
    ))
    for r in rows:
        state = ("DEAD" if not r.get("alive")
                 else "brn" if r.get("brownout") else "yes")
        cells = []
        for _h, w, k, fmt in _REPLICA_COLUMNS:
            v = state if k == "up" else r.get(k)
            if fmt == "s":
                cells.append(f"{str(v if v is not None else '-'):<{w}}")
            elif v is None:
                cells.append(f"{'-':>{w}}")
            else:
                cells.append(f"{v:>{w}{fmt}}")
        out.append("  ".join(cells))
    return "\n".join(out)


def render(snap: dict) -> str:
    """One dashboard frame."""
    state = []
    state.append("BROWNOUT" if snap["brownout"] else "ok")
    if not snap["accepting"]:
        state.append("draining")
    head = (
        f"netrep serve{' fleet' if snap.get('fleet') else ''} · "
        f"up {snap['uptime_s']:.0f}s · "
        f"inflight {snap['inflight']} · packs {snap['packs']} · "
        f"state {'/'.join(state)}"
    )
    if snap.get("slo_s") is not None:
        head += (f" · slo {snap['slo_s']:g}s "
                 f"(budget {snap.get('slo_budget', 0):g})")
    parts = [head]
    if snap.get("replicas"):
        parts.append(render_replica_table(snap["replicas"]))
    if snap["tenants"]:
        parts.append(render_tenant_table(snap["tenants"]))
    elif not snap.get("replicas"):
        parts.append("(no tenants registered)")
    return "\n".join(parts)


def run_top(args) -> int:
    """CLI entry (``python -m netrep_tpu top --socket PATH``): fetch the
    daemon's ``stats`` op, render (or dump JSON), loop unless ``--once``.
    Backend-free — it only speaks the wire."""
    from .client import SocketClient

    try:
        client = SocketClient(args.socket, timeout=args.timeout)
    except OSError as e:
        print(f"cannot connect to serve daemon at {args.socket!r}: {e}",
              file=sys.stderr)
        return 1
    try:
        while True:
            snap = snapshot(client.stats())
            if args.json:
                print(json.dumps(snap), flush=True)
            else:
                if not args.once:
                    # ANSI clear + home — the refresh-loop dashboard
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(render(snap), flush=True)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        try:
            client.close()
        except OSError:
            pass
