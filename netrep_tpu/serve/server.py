"""`python -m netrep_tpu serve` — the always-on daemon (ISSUE 7).

Transport is deliberately minimal: a unix-domain socket (or stdin/stdout)
speaking one JSON object per line — no HTTP framework dependency. Each
connection is handled on its own thread; every op gets exactly one JSON
response line. The in-process scheduler
(:class:`~netrep_tpu.serve.scheduler.PreservationServer`) does all the
work; this module adds the wire, the `/metrics`-style scrape surface, and
the drain protocol:

**SIGTERM/SIGINT → graceful drain**: the listener stops accepting, every
queued and in-flight request finishes (bounded by ``--drain-timeout-s``;
past the bound the remainder is journaled as requeued-on-restart —
ISSUE 10), pooled engines release their device arrays, the telemetry
``serve_start``/``serve_end`` span closes, and the process exits 0 with a
final ``{"serve": "drained", ...}`` line — the contract the
``tpu_watch.sh`` serve drill asserts.

**Crash safety (ISSUE 10)**: ``--journal`` (default on) write-ahead
journals every accepted request before admission; ``--recover`` replays
the journal on boot. **Wire hardening**: request lines are read through
one bounded reader (:func:`read_op_line`) — oversized lines, bad JSON,
non-object ops, and unknown ops each get a structured error response
plus a ``request_malformed`` event, and the connection loop stays alive.

Ops::

    {"op": "ping"}
    {"op": "register_fixture", "tenant": "a", "prefix": "fx",
     "genes": 120, "modules": 3, "seed": 7}
    {"op": "register", "tenant": "a", "name": "d",
     "correlation": [[...]], "network": [[...]], "data": [[...]],
     "assignments": {"node_0": "1", ...}}
    {"op": "analyze", "tenant": "a", "discovery": "d", "test": "t",
     "n_perm": 2000, "seed": 1, "adaptive": false,
     "deadline_s": 30.0, "idempotency_key": "client-chosen"}
    {"op": "metrics"}   → Prometheus text exposition
    {"op": "stats"}
    {"op": "adopt_journal", "path": "...", "datasets_only": false}
                        → fleet failover (ISSUE 14): replay a dead
                          peer's shipped journal copy (datasets_only
                          seeds a fresh autoscaled replica, ISSUE 19)
    {"op": "shutdown"}  → initiates the same drain as SIGTERM
    {"op": "evict_notice", "grace_s": 30.0}
                        → noticed preemption (ISSUE 19): start the
                          bounded drain now. Against a FLEET socket it
                          takes {"replica": "r1"} and performs the full
                          handoff (ring removal → drain → journal-tail
                          ship → peer adoption) before the host dies

A rejected admission (queue full / brownout shedding) answers
``{"ok": false, "retryable": true, "retry_after_s": <hint>}`` — the
client backs off and retries under the SAME idempotency key.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading

import numpy as np

from .protocol import encode_arrays
from .scheduler import PreservationServer, QueueFull, ServeConfig, ServeError

#: wire-hardening bound (ISSUE 10): one request line may not exceed this —
#: an oversized line gets a structured error (+ ``request_malformed``
#: telemetry) and the connection loop stays alive, instead of an
#: unbounded read buffering a hostile payload
MAX_LINE_BYTES = 32 * 1024 * 1024


def _malformed(server: PreservationServer, reason: str) -> dict:
    """Structured malformed-request response + the pinned telemetry
    event; the handler loop continues — a bad line must never tear down
    the connection (the ISSUE 10 wire-hardening satellite)."""
    if server.tel is not None:
        server.tel.emit("request_malformed", reason=reason[:200])
    return {"ok": False, "error": f"malformed request: {reason}",
            "malformed": True}


def dispatch_op(server: PreservationServer, op: dict,
                stop: threading.Event) -> dict:
    """Execute one wire op against the in-process server; returns the
    response dict (``ok`` always present). Shared by the socket and stdio
    transports. Never raises: unknown ops, bad payload shapes, and even
    unexpected internal errors come back as structured error responses so
    the connection loop stays alive."""
    if not isinstance(op, dict):
        return _malformed(server, f"op must be a JSON object, "
                                  f"got {type(op).__name__}")
    try:
        kind = op.get("op")
        if kind == "ping":
            return {"ok": True, "pong": True}
        if kind == "register_fixture":
            kw = {k: int(op[k]) for k in ("genes", "modules", "n_samples",
                                          "seed") if k in op}
            fixture = server.register_fixture(
                str(op["tenant"]), str(op.get("prefix", "fx")), **kw
            )
            return {"ok": True, "fixture": fixture}
        if kind == "register":
            data = op.get("data")
            network = op.get("network")
            correlation = op.get("correlation")
            beta = op.get("beta")
            # data-only atlas payload (ISSUE 9): data + beta, no matrices
            # — the scheduler validates the combination either way
            if isinstance(beta, list):
                beta = tuple(beta)
            digest = server.register_dataset(
                str(op["tenant"]), str(op["name"]),
                network=None if network is None
                else np.asarray(network, dtype=np.float64),
                correlation=None if correlation is None
                else np.asarray(correlation, dtype=np.float64),
                data=None if data is None
                else np.asarray(data, dtype=np.float64),
                assignments=op.get("assignments"),
                beta=beta,
            )
            return {"ok": True, "digest": digest}
        if kind == "analyze":
            kw = {}
            for k in ("modules", "n_perm", "seed", "alternative",
                      "adaptive", "deadline_s", "idempotency_key",
                      "trace_ctx"):
                if k in op and op[k] is not None:
                    kw[k] = op[k]
            result = server.analyze(
                str(op["tenant"]), str(op["discovery"]), op["test"],
                timeout=float(op.get("timeout", 600.0)), **kw,
            )
            return {"ok": True, "result": encode_arrays(result)}
        if kind == "adopt_journal":
            # fleet failover (ISSUE 14): the coordinator hands this
            # replica its dead peer's shipped journal copy — replay it
            # into the live server (register datasets, answer duplicates
            # from journaled results, re-queue unfinished requests).
            # datasets_only (ISSUE 19) seeds a freshly spawned replica
            # with registrations alone — the peer keeps its own work
            summary = server.adopt_journal(
                str(op["path"]),
                datasets_only=bool(op.get("datasets_only", False)),
            )
            return {"ok": True, "adopted": summary}
        if kind == "metrics":
            return {"ok": True, "text": server.metrics_text()}
        if kind == "stats":
            return {"ok": True, "stats": server.stats()}
        if kind == "shutdown":
            stop.set()
            return {"ok": True, "draining": True}
        if kind == "evict_notice":
            # single-replica eviction notice (ISSUE 19): the host is
            # going away in grace_s — begin the same bounded drain as
            # SIGTERM now (the fleet coordinator uses its own handoff
            # path; this op is the standalone-daemon form)
            stop.set()
            return {"ok": True, "draining": True, "evict": True,
                    "grace_s": float(op.get("grace_s") or 30.0)}
        if kind == "dump":
            # live-forensics wire op (ISSUE 20): collect a diagnostic
            # bundle from the running server — flight ring, env, and the
            # journal's REDACTED tail — without stopping anything
            from ..utils import bundle

            path = bundle.collect(
                dest=(str(op["dest"]) if op.get("dest") else None),
                reason=str(op.get("reason") or "dump"),
                telemetry=server.tel,
                journal=server.config.journal,
            )
            return {"ok": True, "bundle": path}
        return _malformed(server, f"unknown op {kind!r}")
    except QueueFull as e:
        # admission-control rejection: retryable by contract, with the
        # server's backlog-drain hint when it has one (ISSUE 10)
        resp = {"ok": False, "error": f"QueueFull: {e}", "retryable": True}
        if e.retry_after_s is not None:
            resp["retry_after_s"] = float(e.retry_after_s)
        return resp
    except (ServeError, TimeoutError, KeyError, TypeError,
            ValueError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}
    # netrep: allow(exception-taxonomy) — wire boundary: one malformed/failed op becomes that client's error line, the daemon keeps serving
    except Exception as e:  # the handler loop must survive anything
        return {"ok": False,
                "error": f"internal error: {type(e).__name__}: {e}"}


def read_op_line(rfile, server: PreservationServer):
    """Read + parse one bounded request line. Returns ``(op, None)`` for
    a parsed op, ``(None, resp)`` for a line that must be answered with a
    structured error (oversized, bad JSON — the loop continues), and
    ``(None, None)`` on EOF. Shared by the socket and stdio transports
    so both survive hostile input identically."""
    line = rfile.readline(MAX_LINE_BYTES + 1)
    if not line:
        return None, None
    if len(line) > MAX_LINE_BYTES and not line.endswith("\n"):
        # discard the rest of the oversized line so the next one parses
        while True:
            chunk = rfile.readline(MAX_LINE_BYTES)
            if not chunk or chunk.endswith("\n"):
                break
        return None, _malformed(
            server, f"request line exceeds {MAX_LINE_BYTES} bytes"
        )
    line = line.strip()
    if not line:
        return None, {"ok": True, "empty": True}
    try:
        return json.loads(line), None
    except json.JSONDecodeError as e:
        return None, _malformed(server, f"bad JSON: {e}")


def _handle_conn(server: PreservationServer, conn: socket.socket,
                 stop: threading.Event) -> None:
    with conn:
        rfile = conn.makefile("r", encoding="utf-8")
        while True:
            op, resp = read_op_line(rfile, server)
            if op is None and resp is None:
                return
            if resp is not None and resp.get("empty"):
                continue
            if resp is None:
                resp = dispatch_op(server, op, stop)
            try:
                conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
            except OSError:
                return
            if stop.is_set():
                return


def serve_daemon(args) -> int:
    """CLI entry (``python -m netrep_tpu serve``); see the module
    docstring. Returns the process exit code."""
    from ..utils.config import EngineConfig

    journal = None if args.no_journal else args.journal
    recover = getattr(args, "recover", None)
    if isinstance(recover, str):
        journal = recover      # `--recover JOURNAL` names the journal
    if recover and journal is None:
        print("serve --recover needs a journal (use --journal PATH or "
              "--recover JOURNAL instead of --no-journal)",
              file=sys.stderr)
        return 2
    cfg = ServeConfig(
        max_queue=args.max_queue,
        max_pack=args.max_pack,
        pool_size=args.pool_size,
        engine=EngineConfig(chunk_size=args.chunk, autotune=False),
        default_n_perm=args.n_perm,
        telemetry=args.telemetry,
        fault_policy=True if os.environ.get("NETREP_FAULT_PLAN") else None,
        journal=journal,
        recover=bool(recover),
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=getattr(args, "checkpoint_every", 4096),
        brownout_enter_s=args.brownout_enter_s,
        brownout_exit_s=args.brownout_exit_s,
        brownout_rate_pps=args.brownout_rate,
        fleet_label=getattr(args, "fleet_label", None),
        aot_export=(True if getattr(args, "aot_export", False)
                    else None),
    )
    server = PreservationServer(cfg)
    stop = threading.Event()

    def _drain_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _drain_signal)
    signal.signal(signal.SIGINT, _drain_signal)

    if hasattr(signal, "SIGUSR2"):
        def _dump_signal(signum, frame):
            # live forensics on demand (ISSUE 20): `kill -USR2 <pid>`
            # drops a diagnostic bundle beside the process without
            # touching the serve loop — same collection as the `dump`
            # wire op, loud-never-fatal
            from ..utils import bundle

            try:
                path = bundle.collect(reason="sigusr2",
                                      telemetry=server.tel,
                                      journal=server.config.journal)
                print(f"SIGUSR2: diagnostic bundle at {path}",
                      file=sys.stderr, flush=True)
            # netrep: allow(exception-taxonomy) — a forensics failure inside a signal handler must never kill a serving daemon
            except Exception as e:
                print(f"SIGUSR2: bundle collection failed: {e}",
                      file=sys.stderr, flush=True)

        signal.signal(signal.SIGUSR2, _dump_signal)

    if args.socket:
        path = args.socket
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(path)
        listener.listen(16)
        listener.settimeout(0.25)
        print(json.dumps({"serve": "ready", "socket": path,
                          "pid": os.getpid()}), flush=True)
        try:
            while not stop.is_set():
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    continue
                threading.Thread(
                    target=_handle_conn, args=(server, conn, stop),
                    daemon=True,
                ).start()
        finally:
            listener.close()
            try:
                os.unlink(path)
            except OSError:
                pass
    else:
        # stdio mode: one JSON op per stdin line, one response per stdout
        # line; EOF drains. Useful for subprocess embedding and debugging.
        print(json.dumps({"serve": "ready", "stdio": True,
                          "pid": os.getpid()}), flush=True)
        while True:
            op, resp = read_op_line(sys.stdin, server)
            if op is None and resp is None:
                break
            if resp is not None and resp.get("empty"):
                continue
            if resp is None:
                resp = dispatch_op(server, op, stop)
            print(json.dumps(resp), flush=True)
            if stop.is_set():
                break

    # graceful drain: queued + in-flight work finishes (bounded by
    # --drain-timeout-s: the remainder is journaled as requeued-on-restart
    # instead of draining unboundedly), engines release, the serve span
    # closes — then one final parseable line
    server.close(drain=True, timeout=args.drain_timeout)
    st = server.stats()
    done = sum(t["done"] for t in st["tenants"].values())
    print(json.dumps({"serve": "drained", "requests_done": done,
                      "requests_requeued": server._last_drain_requeued,
                      "packs": st["packs"]}), flush=True)
    return 0
