"""`python -m netrep_tpu serve` — the always-on daemon (ISSUE 7).

Transport is deliberately minimal: a unix-domain socket (or stdin/stdout)
speaking one JSON object per line — no HTTP framework dependency. Each
connection is handled on its own thread; every op gets exactly one JSON
response line. The in-process scheduler
(:class:`~netrep_tpu.serve.scheduler.PreservationServer`) does all the
work; this module adds the wire, the `/metrics`-style scrape surface, and
the drain protocol:

**SIGTERM/SIGINT → graceful drain**: the listener stops accepting, every
queued and in-flight request finishes (bounded by ``--drain-timeout``),
pooled engines release their device arrays, the telemetry
``serve_start``/``serve_end`` span closes, and the process exits 0 with a
final ``{"serve": "drained", ...}`` line — the contract the
``tpu_watch.sh`` serve drill asserts.

Ops::

    {"op": "ping"}
    {"op": "register_fixture", "tenant": "a", "prefix": "fx",
     "genes": 120, "modules": 3, "seed": 7}
    {"op": "register", "tenant": "a", "name": "d",
     "correlation": [[...]], "network": [[...]], "data": [[...]],
     "assignments": {"node_0": "1", ...}}
    {"op": "analyze", "tenant": "a", "discovery": "d", "test": "t",
     "n_perm": 2000, "seed": 1, "adaptive": false}
    {"op": "metrics"}   → Prometheus text exposition
    {"op": "stats"}
    {"op": "shutdown"}  → initiates the same drain as SIGTERM
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading

import numpy as np

from .protocol import encode_arrays
from .scheduler import PreservationServer, ServeConfig, ServeError


def dispatch_op(server: PreservationServer, op: dict,
                stop: threading.Event) -> dict:
    """Execute one wire op against the in-process server; returns the
    response dict (``ok`` always present). Shared by the socket and stdio
    transports."""
    try:
        kind = op.get("op")
        if kind == "ping":
            return {"ok": True, "pong": True}
        if kind == "register_fixture":
            kw = {k: int(op[k]) for k in ("genes", "modules", "n_samples",
                                          "seed") if k in op}
            fixture = server.register_fixture(
                str(op["tenant"]), str(op.get("prefix", "fx")), **kw
            )
            return {"ok": True, "fixture": fixture}
        if kind == "register":
            data = op.get("data")
            network = op.get("network")
            correlation = op.get("correlation")
            beta = op.get("beta")
            # data-only atlas payload (ISSUE 9): data + beta, no matrices
            # — the scheduler validates the combination either way
            if isinstance(beta, list):
                beta = tuple(beta)
            digest = server.register_dataset(
                str(op["tenant"]), str(op["name"]),
                network=None if network is None
                else np.asarray(network, dtype=np.float64),
                correlation=None if correlation is None
                else np.asarray(correlation, dtype=np.float64),
                data=None if data is None
                else np.asarray(data, dtype=np.float64),
                assignments=op.get("assignments"),
                beta=beta,
            )
            return {"ok": True, "digest": digest}
        if kind == "analyze":
            kw = {}
            for k in ("modules", "n_perm", "seed", "alternative",
                      "adaptive", "deadline_s"):
                if k in op and op[k] is not None:
                    kw[k] = op[k]
            result = server.analyze(
                str(op["tenant"]), str(op["discovery"]), op["test"],
                timeout=float(op.get("timeout", 600.0)), **kw,
            )
            return {"ok": True, "result": encode_arrays(result)}
        if kind == "metrics":
            return {"ok": True, "text": server.metrics_text()}
        if kind == "stats":
            return {"ok": True, "stats": server.stats()}
        if kind == "shutdown":
            stop.set()
            return {"ok": True, "draining": True}
        return {"ok": False, "error": f"unknown op {kind!r}"}
    except (ServeError, TimeoutError, KeyError, TypeError,
            ValueError) as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def _handle_conn(server: PreservationServer, conn: socket.socket,
                 stop: threading.Event) -> None:
    with conn:
        rfile = conn.makefile("r", encoding="utf-8")
        for line in rfile:
            line = line.strip()
            if not line:
                continue
            try:
                op = json.loads(line)
            except json.JSONDecodeError as e:
                resp = {"ok": False, "error": f"bad JSON: {e}"}
            else:
                resp = dispatch_op(server, op, stop)
            try:
                conn.sendall((json.dumps(resp) + "\n").encode("utf-8"))
            except OSError:
                return
            if stop.is_set():
                return


def serve_daemon(args) -> int:
    """CLI entry (``python -m netrep_tpu serve``); see the module
    docstring. Returns the process exit code."""
    from ..utils.config import EngineConfig

    cfg = ServeConfig(
        max_queue=args.max_queue,
        max_pack=args.max_pack,
        pool_size=args.pool_size,
        engine=EngineConfig(chunk_size=args.chunk, autotune=False),
        default_n_perm=args.n_perm,
        telemetry=args.telemetry,
        fault_policy=True if os.environ.get("NETREP_FAULT_PLAN") else None,
    )
    server = PreservationServer(cfg)
    stop = threading.Event()

    def _drain_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _drain_signal)
    signal.signal(signal.SIGINT, _drain_signal)

    if args.socket:
        path = args.socket
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(path)
        listener.listen(16)
        listener.settimeout(0.25)
        print(json.dumps({"serve": "ready", "socket": path,
                          "pid": os.getpid()}), flush=True)
        try:
            while not stop.is_set():
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    continue
                threading.Thread(
                    target=_handle_conn, args=(server, conn, stop),
                    daemon=True,
                ).start()
        finally:
            listener.close()
            try:
                os.unlink(path)
            except OSError:
                pass
    else:
        # stdio mode: one JSON op per stdin line, one response per stdout
        # line; EOF drains. Useful for subprocess embedding and debugging.
        print(json.dumps({"serve": "ready", "stdio": True,
                          "pid": os.getpid()}), flush=True)
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                op = json.loads(line)
            except json.JSONDecodeError as e:
                resp = {"ok": False, "error": f"bad JSON: {e}"}
            else:
                resp = dispatch_op(server, op, stop)
            print(json.dumps(resp), flush=True)
            if stop.is_set():
                break

    # graceful drain: queued + in-flight work finishes, engines release,
    # the serve span closes — then one final parseable line
    server.close(drain=True, timeout=args.drain_timeout)
    st = server.stats()
    done = sum(t["done"] for t in st["tenants"].values())
    print(json.dumps({"serve": "drained", "requests_done": done,
                      "packs": st["packs"]}), flush=True)
    return 0
