"""Replica lifecycle state machine (ISSUE 19).

The replica lifecycle grew by accretion across PRs 14/15 — ``alive()``
checks, ``dead`` events, respawn generation suffixes — with no single
place that says what states exist and which moves between them are
legal. This module pins it::

    spawning ──► ready ──► draining ──► dead ──► spawning (gen+1)
        │          │                      ▲
        │          └──────────────────────┤   (unnoticed loss:
        └─────────────────────────────────┘    SIGKILL, crash, wedge)

- **spawning**: the process/worker is booting; not on the ring yet.
- **ready**: serving — on the ring, shipping its journal.
- **draining**: leaving *on purpose* (autoscale retire, eviction
  notice, fleet close): removed from the ring first, finishing or
  journaling its backlog, tail pre-shipped to the peer. The state that
  makes a noticed eviction a *handoff* instead of a failover.
- **dead**: gone. A respawn re-enters ``spawning`` with the generation
  bumped (``r0 → r0.g1`` in the daemon fleet) — a fresh journal that
  never replays work the peer already adopted.

Every transition emits ONE ``replica_state`` telemetry event carrying
the ``replica`` label plus ``prev``/``to``/``gen``/``reason`` — the
``--recovery`` timeline and ``telemetry`` replica section render the
machine directly from the stream. Illegal transitions raise
:class:`IllegalTransition`: a coordinator bug must fail loudly at the
transition site, not surface later as a replica in two states at once.

:class:`FleetCoordinator`, :class:`DaemonReplica`, and
:class:`InProcessReplica` all route their state changes through
:class:`ReplicaLifecycle` (see :mod:`netrep_tpu.serve.fleet`); the
legal-move table is pinned in tests/test_fleet_autoscale.py.
"""

from __future__ import annotations

import threading

from .scheduler import ServeError

#: the four replica states, in nominal order
SPAWNING = "spawning"
READY = "ready"
DRAINING = "draining"
DEAD = "dead"

STATES = (SPAWNING, READY, DRAINING, DEAD)

#: the complete legal-move table — anything absent raises. Pinned in
#: tests/test_fleet_autoscale.py: adding an edge is a contract change.
LEGAL_TRANSITIONS = frozenset({
    (SPAWNING, READY),      # boot completed: socket up / worker running
    (SPAWNING, DEAD),       # boot failure (never reached the ring)
    (READY, DRAINING),      # retire / eviction notice / fleet close
    (READY, DEAD),          # unnoticed loss: SIGKILL, crash, wedge
    (DRAINING, DEAD),       # drain finished (or its bounded grace did)
    (DEAD, SPAWNING),       # respawn — generation bumps (g+1)
})


class IllegalTransition(ServeError):
    """A lifecycle move outside :data:`LEGAL_TRANSITIONS` — a
    coordinator bug (e.g. draining an already-dead replica). Raised at
    the transition site so the broken control flow is the stack trace,
    not a replica wedged in two states."""


class ReplicaLifecycle:
    """One replica's lifecycle: current state, generation counter, and
    the telemetry emission every transition owes. Thread-safe — the
    health loop, the autoscaler, and client threads all observe it."""

    def __init__(self, rid: str, *, generation: int = 0,
                 telemetry=None, parent: str | None = None):
        self.rid = rid
        self._state = SPAWNING
        self._generation = int(generation)
        self._tel = telemetry
        self._parent = parent
        self._lock = threading.Lock()

    def bind(self, telemetry, parent: str | None = None) -> None:
        """Attach the coordinator's telemetry bus (and its serve span as
        the parent) — replica handles are built before the coordinator
        exists, so the bus arrives at ``join`` time."""
        with self._lock:
            self._tel = telemetry
            self._parent = parent

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def transition(self, to: str, *, reason: str = "", **data) -> str:
        """Move to ``to`` (validating against the pinned table), bump
        the generation on a respawn (``dead → spawning``), and emit the
        ``replica_state`` event. Returns the new state."""
        if to not in STATES:
            raise IllegalTransition(
                f"replica {self.rid}: unknown lifecycle state {to!r}"
            )
        with self._lock:
            prev = self._state
            if (prev, to) not in LEGAL_TRANSITIONS:
                raise IllegalTransition(
                    f"replica {self.rid}: illegal lifecycle transition "
                    f"{prev!r} -> {to!r} (reason={reason!r})"
                )
            if prev == DEAD and to == SPAWNING:
                self._generation += 1
            self._state = to
            gen = self._generation
            tel, parent = self._tel, self._parent
        if tel is not None:
            tel.emit("replica_state", replica=self.rid, prev=prev,
                     to=to, gen=gen, reason=reason, parent=parent,
                     **data)
        return to
