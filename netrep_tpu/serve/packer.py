"""Cross-request dispatch packing (ISSUE 7).

The serving workload is many small preservation requests against datasets
registered once per tenant: same matrices, different module sets, seeds,
permutation budgets. Run one at a time, each request pays a full jit
compile and a full chain of per-chunk dispatch overheads for a few dozen
modules of actual work. This module turns N compatible requests into ONE
engine run:

- :class:`PackedEngine` — a :class:`~netrep_tpu.parallel.engine
  .PermutationEngine` whose module list is the UNION of the packed
  requests' modules, re-bucketed into shared module-size buckets, with
  two per-request identities preserved exactly:

  * **slice offsets** are request-local (``_slice_offsets`` override):
    module k of request r slices the drawn permutation at the offset its
    stand-alone run would use, so slices of different requests may
    overlap — the requests are independent analyses sharing a dispatch,
    not one disjoint label shuffle;
  * **RNG streams** are per request (*key groups*): the chunk draws one
    permutation per group from ``fold_in(key_r, i)``
    (:func:`~netrep_tpu.parallel.engine._perm_keys_grouped_jit`), so a
    packed module sees bit-for-bit the index sets its stand-alone run
    gathers at the same permutation indices.

  Together these make a served result BIT-IDENTICAL to the direct
  ``module_preservation()`` call with the same seed (pinned in
  tests/test_serve.py), while the pack shares compiled programs, device
  matrices, and per-chunk dispatch overhead across requests.

- :class:`PackMonitor` — the retirement controller handed to
  :meth:`~netrep_tpu.parallel.engine.PermutationEngine
  .run_null_monitored`: each request's modules retire at its own
  ``n_perm`` ceiling (and, when the request is adaptive, by its own
  per-request :class:`~netrep_tpu.ops.sequential.StopMonitor` at the
  same chunk boundaries its stand-alone run decides at), exiting the
  shared dispatch via the engine's existing retirement re-bucketing —
  adaptive early-stopping as the latency-SLO mechanism.

- :func:`run_pack` — observed pass + monitored null + per-request result
  extraction (exact Phipson–Smyth p-values per request at its own
  permutation count and total permutation space).

v1 scope: replicated matrices, no mesh, gather modes ``direct``/``mxu``
(the serve tier-1 surface is CPU). Row-sharded and fused packs raise.
"""

from __future__ import annotations

import dataclasses
import hashlib
from functools import partial

import numpy as np

import jax

from ..ops import pvalues as pv
from ..ops import stats as jstats
from ..ops.oracle import N_STATS
from ..ops.sequential import StopMonitor, StopRule
from ..parallel.engine import (
    ModuleSpec, PermutationEngine, _idx_blocks_grouped,
    _perm_keys_grouped_jit,
)
from ..utils.config import EngineConfig


@dataclasses.dataclass
class RequestPlan:
    """One request's stand-alone-run identity inside a pack: the module
    specs (in the order ``module_preservation`` would keep them), the
    permutation pool, budget, seed, and p-value conventions. ``base`` is
    the request's global module offset in the pack, assigned by
    :func:`assign_bases`."""

    labels: list
    specs: list[ModuleSpec]
    counts: dict
    pool: np.ndarray
    n_perm: int
    seed: int
    alternative: str = "greater"
    adaptive: bool = False
    rule: object | None = None
    base: int = 0
    #: monotonic deadline (ISSUE 10): when set, the pack monitor cancels
    #: this request's still-active modules at the first chunk boundary
    #: past it (``StopMonitor.force_retire`` — the same retirement
    #: re-bucketing exit a statistical decision takes, so pack survivors
    #: are untouched); None = never expires (the PR 7 behavior)
    deadline: float | None = None
    #: warm-start priors (ISSUE 17 incremental re-analysis): optional
    #: ``(hi, lo, n_used)`` count-space tallies from a prior run of this
    #: cell, seeded into the adaptive child monitor's decision rules
    #: (:meth:`~netrep_tpu.ops.sequential.StopMonitor.seed_priors`);
    #: ignored for non-adaptive plans. Reported tallies/p-values stay
    #: fresh-draw-only, so packed warm-started results remain
    #: bit-identical to the solo warm-started run.
    priors: object | None = None

    @property
    def k(self) -> int:
        return len(self.specs)

    @property
    def sizes(self) -> list[int]:
        return [m.size for m in self.specs]

    _sig: str | None = dataclasses.field(default=None, repr=False,
                                         compare=False)

    def signature(self) -> str:
        """Structural identity of this plan for the warm program pool:
        module labels/sizes/index content (seed, n_perm, alternative are
        run-time data — two plans differing only there share compiled
        programs). Memoized — the scheduler consults it per pack."""
        if self._sig is not None:
            return self._sig
        h = hashlib.blake2b(digest_size=8)
        for m in self.specs:
            h.update(str(m.label).encode() + b"|")
            h.update(np.ascontiguousarray(m.disc_idx, dtype=np.int64))
            h.update(np.ascontiguousarray(m.test_idx, dtype=np.int64))
        h.update(np.ascontiguousarray(self.pool, dtype=np.int64))
        self._sig = h.hexdigest()
        return self._sig


def assign_bases(plans: list[RequestPlan]) -> int:
    """Assign each plan its contiguous global module offset in the pack;
    returns the union module count."""
    base = 0
    for p in plans:
        p.base = base
        base += p.k
    return base


class PackedEngine(PermutationEngine):
    """Permutation engine over the UNION of several requests' modules with
    per-request slice offsets and RNG key groups (module docstring).

    ``request_modules`` is one ``[ModuleSpec, ...]`` list per packed
    request, in :func:`assign_bases` order; ``key=`` arguments to the run
    methods are then the same-length list of per-request seeds (or typed
    keys). All requests must share the (discovery, test) matrices and the
    permutation pool — the scheduler's pack key guarantees it.
    """

    def __init__(self, disc_corr, disc_net, disc_data, test_corr, test_net,
                 test_data, request_modules, pool,
                 config: EngineConfig = EngineConfig(), mesh=None,
                 observed_cache=None):
        if mesh is not None or config.matrix_sharding == "row":
            raise ValueError(
                "packed serve engines run replicated and mesh-free (v1); "
                "drop the mesh / matrix_sharding='row'"
            )
        mods, offs, groups = [], [], []
        pool_size = int(np.asarray(pool).size)
        for g, specs in enumerate(request_modules):
            off = 0
            for m in specs:
                mods.append(m)
                offs.append(off)
                groups.append(g)
                off += m.size
            # the per-REQUEST oversubscription check `_check_pool` waives
            if off > pool_size:
                raise ValueError(
                    f"packed request {g}: module sizes (total {off}) exceed "
                    f"the null candidate pool ({pool_size})"
                )
        if not mods:
            raise ValueError("a pack needs at least one module")
        self._packed_offsets = np.asarray(offs, dtype=np.int64)
        self._module_group = np.asarray(groups, dtype=np.int64)
        self.n_groups = len(request_modules)
        super().__init__(disc_corr, disc_net, disc_data, test_corr, test_net,
                         test_data, mods, pool, config=config, mesh=None,
                         observed_cache=observed_cache)
        # packed chunks draw one pool shuffle PER KEY GROUP (the overridden
        # chunk_body below); the fused-stats mega-kernel's chunk/counter
        # builders draw the base engine's single-group stream and would
        # silently break the per-request RNG contract — pin the packed
        # engine to the XLA composition until the kernel learns key groups
        self.stat_mode = "xla"
        if self.gather_mode == "fused":
            raise ValueError(
                "gather_mode='fused' is not supported by the packed engine "
                "(v1); use 'direct'/'mxu'/'auto'"
            )
        #: jitted chunk programs keyed by the CURRENT bucket signature —
        #: retirement re-bucketing produces a handful of shrunken
        #: signatures per pack shape, and a warm-pool engine must reuse
        #: their compiled programs across packs instead of re-tracing a
        #: fresh closure every run (jit caches by function identity)
        self._packed_fn_cache: dict = {}

    # -- per-request identity hooks (see PermutationEngine) ----------------

    def _check_pool(self) -> None:
        # per-request totals were checked in __init__; the union of
        # overlapping request-local slices may legitimately exceed the pool
        return

    def _slice_offsets(self, sizes) -> np.ndarray:
        return self._packed_offsets

    # -- key groups --------------------------------------------------------

    def prepare_key(self, key):
        """``key`` is the per-request seed list (ints or typed keys), in
        group order — stacked into a (G,) typed key array."""
        ks = [
            jax.random.key(int(s)) if isinstance(s, (int, np.integer))
            else s
            for s in key
        ]
        if len(ks) != self.n_groups:
            raise ValueError(
                f"packed run needs {self.n_groups} per-request keys, "
                f"got {len(ks)}"
            )
        import jax.numpy as jnp

        return jnp.stack(ks)

    def key_data(self, key):
        return np.asarray(jax.random.key_data(key))

    def perm_keys(self, key, start: int, count: int):
        """(count, G) per-permutation keys — column g carries group g's
        solo-run ``fold_in`` stream (perm axis leading for ``lax.map``)."""
        import jax.numpy as jnp

        return _perm_keys_grouped_jit(key, jnp.uint32(start), int(count))

    # -- fingerprints ------------------------------------------------------

    def autotune_key(self, extra: str = "") -> str:
        """Serve-path compile/throughput fingerprint: the base problem-
        shape key plus the pack's group count, so packed-run compile_span
        events and perf-ledger entries never share a history with the
        stand-alone engine of the same bucket signature."""
        tag = f"packed:{self.n_groups}"
        return super().autotune_key(
            extra=f"{tag}|{extra}" if extra else tag
        )

    def _program_constants(self) -> str:
        """AOT program identity (ISSUE 15): the packed chunk body also
        closes over each bucket's per-module key-group assignment — two
        packs whose modules map to different request groups trace
        different programs and must never share a serialized entry."""
        groups = ";".join(
            ",".join(str(int(self._module_group[p])) for p in b.module_pos)
            for b in self.buckets
        )
        return super()._program_constants() + f"|groups:{groups}"

    def _example_run_key(self):
        return self.prepare_key([0] * self.n_groups)

    def _warm_programs(self) -> tuple[str, ...]:
        # packed runs are materialized-adaptive (run_null_monitored):
        # chunk + observed are the programs a replica's first request
        # compiles; the base streaming builders use the ungrouped key
        # contract and never serve packs
        return ("chunk", "observed")

    # -- chunk program -----------------------------------------------------

    def chunk_body(self):
        """Packed chunk program — the replicated branch of
        :meth:`PermutationEngine.chunk_body` with per-permutation work
        generalized from one drawn permutation to one PER KEY GROUP:
        ``keys`` is ``(C, G)``; each permutation index draws G pool
        shuffles and every bucket gathers each module's slice from its
        group's shuffle (:func:`_idx_blocks_grouped`). Kernels, padding,
        and batching are the base engine's — per-module numerics are
        bit-identical to the stand-alone chunk program."""
        cfg = self.config
        caps_slices_groups = [
            (b.cap, tuple(b.slices),
             tuple(int(self._module_group[p]) for p in b.module_pos))
            for b in self.buckets
        ]
        from ..utils.autotune import resolve_perm_batch

        if self.data_only:
            # atlas tenants (ISSUE 9): no stored matrices — submatrices
            # derive from the gathered data columns, same kernel as the
            # stand-alone data-only engine so packed results stay
            # bit-identical to direct calls
            from ..atlas.modules import (
                data_only_gather_and_stats, normalize_beta_static,
            )

            heuristic = cfg.resolved_perm_batch(
                "direct", jax.default_backend(), self.effective_chunk()
            )
            kernel = partial(
                data_only_gather_and_stats,
                net_beta=normalize_beta_static(self.net_beta),
                n_iter=cfg.power_iters,
                summary_method=cfg.summary_method,
            )
            kernel_axes = (0, 0, None)
        else:
            heuristic = cfg.resolved_perm_batch(
                self.gather_mode, jax.default_backend(),
                self.effective_chunk(),
                bytes_per_perm=self._mxu_bytes_per_perm(
                    int(self._test_corr.shape[-1]),
                    None if self._test_dataT is None
                    else int(self._test_dataT.shape[-1]),
                ),
            )
            kernel = partial(
                jstats.gather_and_stats_mxu if self.gather_mode == "mxu"
                else jstats.gather_and_stats,
                n_iter=cfg.power_iters,
                summary_method=cfg.summary_method,
                net_beta=self.net_beta,
            )
            kernel_axes = (0, 0, None, None, None)
        at_key = self.autotune_key()
        perm_batch, at_cache = resolve_perm_batch(cfg, at_key, heuristic)
        self._applied_perm_batch = perm_batch
        self._autotune_record = (
            (at_cache, at_key, perm_batch) if at_cache is not None else None
        )
        data_only = self.data_only

        def chunk(keys, pool, tc, tn, td, discs):
            # keys: (C, G) typed PRNG keys — row i holds every group's key
            # for permutation index i
            def per_perm(keys_row):
                perms = jax.vmap(
                    lambda k: jax.random.permutation(k, pool)
                )(keys_row)  # (G, P)
                outs_p = []
                for (cap, slices, groups), disc in zip(
                        caps_slices_groups, discs):
                    idx_b = _idx_blocks_grouped(perms, cap, slices, groups)
                    over_mods = jax.vmap(kernel, in_axes=kernel_axes)
                    outs_p.append(
                        over_mods(disc, idx_b, td) if data_only
                        else over_mods(disc, idx_b, tc, tn, td)
                    )
                return outs_p

            return jax.lax.map(per_perm, keys, batch_size=perm_batch)

        return chunk

    def _chunk_fn(self):
        # memoize jitted programs per bucket signature (not just "latest"):
        # each retirement re-bucketing of a repeated pack shape then hits a
        # warm program instead of re-tracing a fresh closure
        sig = tuple(
            (b.cap, tuple(b.slices), tuple(b.module_pos))
            for b in self.buckets
        )
        fn = self._packed_fn_cache.get(sig)
        if fn is None:
            fn = self._build_chunk_fn()
            self._packed_fn_cache[sig] = fn
        else:
            self._program_sources["chunk"] = "memo"
        return fn

    def release(self) -> None:
        self._packed_fn_cache = {}
        super().release()


class GridPackedEngine(PackedEngine):
    """Cross-pair pack (ISSUE 17): :class:`PackedEngine` generalized from
    one shared (discovery, test) pair to one shared TEST dataset with a
    per-request DISCOVERY source — the engine behind a grid column, where
    every cell tests a different cohort's modules in the same test
    cohort.

    ``disc_sources`` is one ``(corr, net, data)`` triple per packed
    request, aligned with ``request_modules``. The feasibility argument,
    pinned bit-identical in tests/test_grid.py: discovery matrices enter
    the chunk program only through the per-bucket *discovery property*
    arrays (plain data operands, one row per module), and the kernels are
    vmapped per module — so a union bucket whose rows were computed from
    each request's own matrices runs every module's numerics exactly as
    its solo engine would. The permutation side (request-local slice
    offsets, per-request RNG key groups) is :class:`PackedEngine`'s
    existing two-identity contract, unchanged.

    Requirements beyond PackedEngine's: every request must share the
    permutation pool byte-for-byte (``null='all'``, or overlap pools that
    coincide — the grid groups cells by pool signature before packing),
    every discovery source must agree on data presence, and matrices must
    be materialized (data-only cells run per-pair)."""

    def __init__(self, disc_sources, test_corr, test_net, test_data,
                 request_modules, pool,
                 config: EngineConfig = EngineConfig(), mesh=None,
                 observed_cache=None):
        if len(disc_sources) != len(request_modules):
            raise ValueError(
                f"got {len(disc_sources)} discovery sources for "
                f"{len(request_modules)} packed requests"
            )
        if any(s[0] is None or s[1] is None for s in disc_sources):
            raise ValueError(
                "cross-pair grid packs need materialized discovery "
                "matrices; data-only cells run per-pair"
            )
        presence = {s[2] is not None for s in disc_sources}
        if len(presence) != 1 or (test_data is not None) not in presence:
            raise ValueError(
                "cross-pair grid packs need every discovery source and "
                "the test dataset to agree on data presence"
            )
        from ..parallel.engine import check_derived_network

        beta = config.network_from_correlation
        if beta is not None:
            # the base engine sample-checks source 0 only
            for i, (dc, dn, _dd) in enumerate(disc_sources[1:], start=1):
                check_derived_network(dc, dn, beta, f"discovery[{i}]")
        self._disc_sources = list(disc_sources)
        self._grid_dev: list | None = None
        self._grid_digests: list[str] | None = None
        super().__init__(
            disc_sources[0][0], disc_sources[0][1], disc_sources[0][2],
            test_corr, test_net, test_data, request_modules, pool,
            config=config, mesh=mesh, observed_cache=observed_cache,
        )
        # checkpoint/AOT identity must cover EVERY discovery source (the
        # base init digested source 0 only)
        from ..utils.checkpoint import content_digest

        self._fingerprint_digest = content_digest(
            [a for s in disc_sources for a in s]
            + [test_corr, test_net, test_data]
        )

    def _bucket_disc_props(self, cap, pos, didx, mask):
        """Per-request discovery props: the bucket's module positions are
        request-contiguous (union order is request-major, by-cap grouping
        preserves ascending position), so the (K, cap) stacks split into
        per-request segments whose rows are computed from that request's
        own matrices — each segment byte-identical to the solo engine's
        bucket build, which is also what makes the ObservedCache keys
        line up across grid and solo runs."""
        if self._grid_dev is None:
            import jax.numpy as jnp

            from ..utils.checkpoint import content_digest

            dev, digs = [], []
            for dc, dn, dd in self._disc_sources:
                dev.append((
                    jnp.asarray(dc, jnp.float32),
                    (None if self.net_beta is not None
                     else jnp.asarray(dn, jnp.float32)),
                    (jnp.asarray(dd, jnp.float32)
                     if self.has_data else None),
                ))
                digs.append(content_digest([dc, dn, dd]))
            self._grid_dev, self._grid_digests = dev, digs
        groups = self._module_group[np.asarray(pos, dtype=np.int64)]
        parts = []
        start = 0
        while start < len(groups):
            g = int(groups[start])
            end = start
            while end < len(groups) and int(groups[end]) == g:
                end += 1
            dc, dn, dd = self._grid_dev[g]
            parts.append(self._props_for(
                self._grid_digests[g], dc, dn, dd, cap,
                didx[start:end], mask[start:end],
            ))
            start = end
        if len(parts) == 1:
            return parts[0]
        import jax.numpy as jnp

        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts
        )


class PackMonitor:
    """Retirement controller for a packed run — the
    :class:`~netrep_tpu.ops.sequential.StopMonitor`-shaped object
    :meth:`~netrep_tpu.parallel.engine.PermutationEngine
    .run_null_monitored` folds each chunk into.

    Per request it applies, at every chunk boundary and in stand-alone-run
    order:

    1. **stop rule** (adaptive requests only): a child
       :class:`StopMonitor` over the request's modules, fed exactly the
       rows its stand-alone run would fold (the final chunk before the
       request's ceiling is truncated to ``n_perm_r - folded``, matching
       the solo loop's partial tail chunk) — decisions are bit-identical;
    2. **ceiling**: once the pack's fold reaches the request's ``n_perm``,
       its remaining modules are force-retired
       (:meth:`StopMonitor.force_retire`) and leave the shared dispatch.

    The pack keeps running while any request still owes permutations;
    ``n_used`` records each module's per-request permutation count for
    the sequential p-values.

    Deadline enforcement (ISSUE 10): a plan with ``deadline`` set is
    checked against ``clock()`` at every chunk boundary; once past it,
    the request's still-active modules are force-retired (they stop
    consuming dispatches; pack survivors are unaffected) and the plan's
    index lands in :attr:`expired` with its deadline miss — the
    scheduler cancels the request instead of returning a result.

    Checkpointing (ISSUE 10): :meth:`state_arrays`/:meth:`restore_state`
    ride the engine checkpoint's ``extra`` channel exactly like
    :class:`StopMonitor` does for solo adaptive runs, so a ``SIGKILL``
    mid-pack resumes from the last chunk boundary — per-request child
    monitors are namespaced ``g<i>_*`` inside the pack's state.

    Cost attribution (ISSUE 13): with :meth:`enable_cost_tracking` on
    (``run_pack`` arms it whenever telemetry is), :meth:`update` records
    each chunk's per-request live-module weights and host-pull bytes, the
    engine loop feeds the chunk's measured dispatch/transfer seconds via
    :meth:`note_chunk_cost`, and :meth:`request_costs` splits every
    chunk's measured cost across the members by their EXACT
    live-module × permutation share at that chunk — integer fields
    (perms, bytes) by largest-remainder, float fields by
    remainder-to-the-last-live-member, and the reported pack totals
    DEFINED as the ordered member sums — so member costs sum bit-exactly
    (f64 host arithmetic) to the pack totals by construction, across
    retirement re-bucketing, deadline expiry, and checkpoint-resumed
    recovery runs. Tracking off (telemetry off) records nothing.
    """

    def __init__(self, plans: list[RequestPlan], observed: np.ndarray,
                 clock=None):
        import time as _time

        self.plans = plans
        self.clock = clock if clock is not None else _time.monotonic
        self.observed = np.asarray(observed, dtype=np.float64)
        self.n_modules = sum(p.k for p in plans)
        if self.observed.shape[0] != self.n_modules:
            raise ValueError(
                f"observed has {self.observed.shape[0]} modules, plans "
                f"describe {self.n_modules}"
            )
        self.active = np.ones(self.n_modules, dtype=bool)
        self.n_used = np.zeros(self.n_modules, dtype=np.int64)
        self.folded = 0
        self.telemetry = None
        #: plan index -> seconds past its deadline when it was cancelled
        self.expired: dict[int, float] = {}
        #: cost-attribution chunk log (ISSUE 13): populated only when
        #: :meth:`enable_cost_tracking` armed it (telemetry on), so the
        #: telemetry-off pack path stays bit-and-behavior-identical
        self._cost_enabled = False
        self._cost_chunks: list[dict] = []
        self.children: list[StopMonitor | None] = []
        for p in plans:
            if p.adaptive:
                child = StopMonitor(
                    self.observed[p.base: p.base + p.k],
                    p.alternative, p.rule or StopRule(),
                )
                if p.priors is not None:
                    # warm start (ISSUE 17): decision rules see the prior
                    # tallies exactly as the solo warm-started run's
                    # monitor does — same chunk boundaries, same decisions
                    child.seed_priors(*p.priors)
                self.children.append(child)
            else:
                self.children.append(None)

    # -- StopMonitor surface ----------------------------------------------

    def active_positions(self) -> np.ndarray:
        return np.flatnonzero(self.active)

    def any_active(self) -> bool:
        return bool(self.active.any())

    def total_evaluated(self) -> int:
        return int(self.n_used.sum())

    def update(self, vals: np.ndarray, take: int) -> np.ndarray:
        """Fold one chunk (``vals``: ``(take, n_active, cells)`` in
        :meth:`active_positions` order); returns the global positions
        retired by this chunk — rule decisions and ceiling exits both."""
        pos = self.active_positions()
        vals = np.asarray(vals, dtype=np.float64)
        done0 = self.folded
        if self._cost_enabled:
            # the chunk that just landed ran with THIS active set: each
            # member's exact share of the dispatch is its live modules ×
            # the chunk's permutation count (the engine computed `take`
            # rows for every active module, fold ceilings notwithstanding)
            live = {}
            for gi, p in enumerate(self.plans):
                c = int(np.count_nonzero(
                    (pos >= p.base) & (pos < p.base + p.k)
                ))
                if c:
                    live[gi] = c
            self._cost_chunks.append({
                "take": int(take), "live": live,
                "bytes": int(vals.nbytes),
            })
        newly: list[np.ndarray] = []
        for p, child in zip(self.plans, self.children):
            cols = np.flatnonzero((pos >= p.base) & (pos < p.base + p.k))
            if not cols.size:
                continue
            gpos = pos[cols]
            # rows this request still owes — the solo run's own final
            # partial chunk when the ceiling lands mid-chunk
            rows = int(min(take, max(0, p.n_perm - done0)))
            if rows > 0:
                if child is not None:
                    child.telemetry = self.telemetry
                    retired = child.update(vals[:rows, cols, :], rows)
                    self.n_used[p.base: p.base + p.k] = child.n_used
                    if retired.size:
                        g = p.base + retired
                        self.active[g] = False
                        newly.append(g)
                else:
                    self.n_used[gpos] += rows
            if done0 + take >= p.n_perm:
                # budget spent at this boundary: the request's surviving
                # modules exit the shared dispatch (SLO/ceiling retirement)
                if child is not None:
                    ceiling = p.base + child.force_retire()
                else:
                    ceiling = gpos
                still = ceiling[self.active[ceiling]]
                if still.size:
                    self.active[still] = False
                    newly.append(still)
        self.folded = done0 + int(take)
        # deadline sweep (ISSUE 10): pack boundaries are the cancellation
        # points — an expired request's surviving modules leave the shared
        # dispatch through the same force_retire exit the ceiling uses,
        # so the pack's other members are bit-identically unaffected
        now = self.clock()
        for gi, p in enumerate(self.plans):
            if p.deadline is None or gi in self.expired or now <= p.deadline:
                continue
            span = np.arange(p.base, p.base + p.k)
            still = span[self.active[span]]
            if still.size:
                self.active[still] = False
                newly.append(still)
                self.expired[gi] = now - p.deadline
        if newly:
            return np.concatenate(newly)
        return np.empty(0, dtype=np.int64)

    # -- cost attribution (ISSUE 13) ---------------------------------------

    def enable_cost_tracking(self) -> None:
        """Arm the per-chunk cost log (``run_pack`` calls this whenever
        telemetry is on; off by default so the telemetry-off path records
        nothing)."""
        self._cost_enabled = True

    def note_chunk_cost(self, dispatch_s: float,
                        transfer_s: float = 0.0) -> None:
        """Engine-loop hook: attach the measured dispatch/transfer
        seconds of the chunk whose weights :meth:`update` just recorded.
        The loop calls it right after folding the chunk, so the last
        un-costed record is always the matching one."""
        if not self._cost_enabled:
            return
        for rec in reversed(self._cost_chunks):
            if "dispatch_s" not in rec:
                rec["dispatch_s"] = float(dispatch_s)
                rec["transfer_s"] = float(transfer_s)
                return

    #: the request_cost fields under the conservation contract
    COST_FIELDS = ("device_s", "transfer_s", "perms", "bytes_to_host",
                   "compile_s_amortized")

    def request_costs(self) -> dict | None:
        """Deterministic per-request cost attribution over the recorded
        chunks; ``None`` when tracking was off or nothing ran.

        Returns ``{"members": [one dict per plan, in plan order],
        "totals": {...}, "measured_device_s": float}``. Per chunk, member
        g's share weight is ``live_modules_g × take``; integer costs
        (``perms``, ``bytes_to_host``) split by largest remainder, float
        costs (``device_s``, ``transfer_s``) by remainder-to-the-last-
        live-member, and ``compile_s_amortized`` (the first-dispatch-
        minus-steady-median estimate) by total weight share. The
        ``totals`` are DEFINED as the ordered (plan-order) f64 sums of
        the member fields, so ``sum(member[f]) == totals[f]`` is an
        identity — bit-exact, pinned in tests — while staying within one
        rounding step of the raw measured sums (``measured_device_s``)."""
        if not self._cost_enabled or not self._cost_chunks:
            return None
        G = len(self.plans)
        perms = [0] * G
        byts = [0] * G
        dev = [0.0] * G
        xfer = [0.0] * G
        disp_series: list[float] = []
        for c in self._cost_chunks:
            live = c["live"]
            take = int(c["take"])
            if not live or take <= 0:
                continue
            order = sorted(live)
            ws = {g: live[g] * take for g in order}
            W = sum(ws.values())
            d_s = float(c.get("dispatch_s", 0.0))
            t_s = float(c.get("transfer_s", 0.0))
            disp_series.append(d_s)
            for g in order:
                perms[g] += take
            b = int(c["bytes"])
            base = {g: b * ws[g] // W for g in order}
            rem = b - sum(base.values())
            for g in sorted(order, key=lambda g: (-(b * ws[g] % W), g)):
                if rem <= 0:
                    break
                base[g] += 1
                rem -= 1
            for g in order:
                byts[g] += base[g]
            for arr, cost in ((dev, d_s), (xfer, t_s)):
                acc = 0.0
                for g in order[:-1]:
                    x = cost * (ws[g] / W)
                    arr[g] += x
                    acc += x
                arr[order[-1]] += cost - acc
        # compile carve-out: the first dispatch absorbed the jit compile;
        # steady state is the median of the rest (the engine's own
        # compile_span convention), amortized by total weight share
        if len(disp_series) >= 2:
            rest = sorted(disp_series[1:])
            comp = max(0.0, disp_series[0] - rest[len(rest) // 2])
        else:
            comp = 0.0
        wtot = [
            sum(c["live"].get(g, 0) * int(c["take"])
                for c in self._cost_chunks)
            for g in range(G)
        ]
        w_all = sum(wtot)
        comp_g = [0.0] * G
        if comp > 0 and w_all > 0:
            live_gs = [g for g in range(G) if wtot[g] > 0]
            acc = 0.0
            for g in live_gs[:-1]:
                x = comp * (wtot[g] / w_all)
                comp_g[g] = x
                acc += x
            comp_g[live_gs[-1]] = comp - acc
        members = [
            {
                "device_s": dev[g], "transfer_s": xfer[g],
                "perms": perms[g], "bytes_to_host": byts[g],
                "compile_s_amortized": comp_g[g],
                "weight": int(wtot[g]),
            }
            for g in range(G)
        ]
        totals: dict = {f: 0 for f in ("perms", "bytes_to_host")}
        totals.update({f: 0.0 for f in ("device_s", "transfer_s",
                                        "compile_s_amortized")})
        for m in members:
            for f in self.COST_FIELDS:
                totals[f] += m[f]
        totals["weight"] = int(w_all)
        return {
            "members": members,
            "totals": totals,
            "measured_device_s": sum(disp_series),
        }

    # -- checkpoint state (ISSUE 10) ---------------------------------------

    def state_arrays(self) -> dict[str, np.ndarray]:
        """Checkpointable pack state — the union tallies plus each
        adaptive child's own state under a ``g<i>_`` namespace (the
        checkpoint ``extra`` channel, same contract as
        :meth:`StopMonitor.state_arrays`)."""
        exp = sorted(self.expired)
        out = {
            "pack_active": self.active,
            "pack_n_used": self.n_used,
            "pack_folded": np.int64(self.folded),
            "pack_expired": np.asarray(exp, dtype=np.int64),
            "pack_expired_miss": np.asarray(
                [self.expired[g] for g in exp], dtype=np.float64
            ),
        }
        for g, child in enumerate(self.children):
            if child is not None:
                for k, v in child.state_arrays().items():
                    out[f"g{g}_{k}"] = v
        return out

    def restore_state(self, extras: dict) -> None:
        """Restore from checkpoint extras (shape-checked); expired plans
        STAY cancelled across the restart — a request whose deadline was
        missed before the crash must not resurrect as a success."""
        try:
            active = extras["pack_active"]
            n_used = extras["pack_n_used"]
            folded = extras["pack_folded"]
        except KeyError:
            raise ValueError(
                "checkpoint has no pack-monitor state (it was written by "
                "a non-packed run); refusing to resume"
            ) from None
        if np.asarray(active).shape != self.active.shape:
            raise ValueError(
                "checkpoint pack state has a different module count; "
                "refusing to resume"
            )
        self.active = np.asarray(active, dtype=bool)
        self.n_used = np.asarray(n_used, dtype=np.int64)
        self.folded = int(folded)
        self.expired = {
            int(g): float(m)
            for g, m in zip(np.asarray(extras.get("pack_expired", []),
                                       dtype=np.int64).ravel(),
                            np.asarray(extras.get("pack_expired_miss", []),
                                       dtype=np.float64).ravel())
        }
        for g, child in enumerate(self.children):
            if child is None:
                continue
            prefix = f"g{g}_"
            child.restore_state({
                k[len(prefix):]: v for k, v in extras.items()
                if k.startswith(prefix)
            })


def run_pack(engine: PackedEngine, plans: list[RequestPlan],
             telemetry=None, fault_policy=None, progress=None,
             checkpoint_path=None, checkpoint_every: int = 8192,
             clock=None) -> list[dict]:
    """Execute one pack: shared observed pass, monitored null over the
    union buckets, then per-request result extraction. Returns one result
    dict per plan (same order) with the exact numbers the stand-alone
    ``module_preservation()`` call produces for that request's seed.

    ``checkpoint_path`` (ISSUE 10) threads the pack through the engine's
    chunk-boundary checkpoint machinery: a crash mid-pack resumes from
    the last saved boundary bit-identically (the pack monitor's state
    rides the checkpoint extras). A plan cancelled by its deadline comes
    back with ``"expired"``/``"deadline_miss_s"`` set instead of being a
    valid result — the scheduler fails it as a deadline miss."""
    observed = np.asarray(engine.observed(), dtype=np.float64)
    monitor = PackMonitor(plans, observed, clock=clock)
    if telemetry is not None:
        # cost attribution rides the telemetry path only (ISSUE 13): the
        # telemetry-off pack records nothing and stays PR 12-identical
        monitor.enable_cost_tracking()
    n_perm_max = max(p.n_perm for p in plans)
    seeds = [p.seed for p in plans]
    nulls, completed, finished = engine.run_null_monitored(
        n_perm_max, seeds, monitor, progress=progress,
        telemetry=telemetry, fault_policy=fault_policy,
        checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
    )
    costs = monitor.request_costs()

    def cost_of(gi: int) -> dict | None:
        if costs is None:
            return None
        return dict(costs["members"][gi],
                    pack_totals=dict(costs["totals"]))

    out = []
    for gi, p in enumerate(plans):
        if gi in monitor.expired:
            res = {
                "expired": True,
                "deadline_miss_s": float(monitor.expired[gi]),
                "n_perm": int(p.n_perm),
                "completed": int(min(monitor.folded, p.n_perm)),
            }
            if costs is not None:
                # an expired request consumed dispatches before its
                # cancellation — its share is attributed, not vanished
                res["cost"] = cost_of(gi)
            out.append(res)
            continue
        obs_r = observed[p.base: p.base + p.k]
        nulls_r = nulls[: p.n_perm, p.base: p.base + p.k, :]
        total_space = pv.total_permutations(p.pool.size, p.sizes)
        completed_r = min(int(completed), p.n_perm)
        if p.adaptive:
            p_values, n_used = pv.sequential_pvalues(
                obs_r, nulls_r, p.alternative, total_nperm=total_space
            )
            p_type = "sequential"
        else:
            p_values = pv.permutation_pvalues(
                obs_r, nulls_r, p.alternative, total_nperm=total_space
            )
            n_used = None
            p_type = "fixed"
        hi, lo, eff = pv.tail_counts(obs_r, nulls_r)
        n_present = np.array([p.counts[lab][0] for lab in p.labels])
        tot = np.array([p.counts[lab][1] for lab in p.labels])
        cost = cost_of(gi)
        out.append({
            **({"cost": cost} if cost is not None else {}),
            "module_labels": list(p.labels),
            "observed": obs_r,
            "p_values": p_values,
            "counts_hi": hi, "counts_lo": lo, "counts_eff": eff,
            "n_perm": int(p.n_perm),
            "completed": completed_r,
            "n_perm_used": n_used,
            "p_type": p_type,
            "alternative": p.alternative,
            "seed": int(p.seed),
            "n_vars_present": n_present,
            "prop_vars_present": n_present / tot,
            "total_size": tot,
            "total_space": total_space,
            "finished": bool(finished),
        })
    return out
