"""``python -m netrep_tpu warmup`` — pre-export the engine program grid
(ISSUE 15).

A fresh process pays a seconds-scale jit-compile tax on its first null
run — the one cost the warm engine pool cannot amortize across replica
boots, CLI runs, or fleet respawns. This module populates the AOT store
(:mod:`netrep_tpu.utils.aot`) ahead of time: for each requested problem
shape it builds the engines a serving replica (the packed serve path)
and a direct ``module_preservation`` call would build, traces their
bucketed null programs once (chunk body, superchunk scan, adaptive
counter, observed pass, grouped-keys helpers), serializes them with
``jax.export``, and compiles them once into the persistent XLA compile
cache — after which any process sharing the store answers its first
request at steady-state speed (``compile_span ~0``, ``source: aot``).

``--measure`` is the proof half: in a (fresh) process it builds the
serve-path engine for the same shape, runs one null, and reports the
run's measured ``compile_span`` and its acquisition source — the number
``benchmarks/serve_load.py --warmstart`` and the ``tpu_watch.sh``
warmstart step assert on.

Shapes are fixture-parameterized exactly like the serve plane's
``register_fixture`` (same generator, same module assignment), so
warming ``--genes/--modules/--samples`` warms precisely the programs a
fixture-driven replica serves. Arbitrary registered datasets warm
themselves instead: replicas export-on-miss (``ServeConfig.aot_export``)
and preload at boot.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np


def _fixture(genes: int, modules: int, samples: int, seed: int):
    """The serve plane's fixture: same generator + assignment derivation
    as ``PreservationServer.register_fixture``, so shapes match bit-for-
    bit."""
    from .data import make_mixed_pair

    mixed = make_mixed_pair(genes, modules, n_samples=samples, seed=seed)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    return mixed, assign


def _serve_engine(genes: int, modules: int, samples: int, seed: int,
                  chunk: int, n_perm: int | None):
    """The EXACT packed engine a serve replica's first request for this
    fixture builds (solo pack): derived through the scheduler's own
    registration + plan + builder path, so the program identity cannot
    drift from production."""
    from .serve.scheduler import PreservationServer, ServeConfig
    from .utils.config import EngineConfig

    srv = PreservationServer(
        ServeConfig(engine=EngineConfig(chunk_size=chunk, autotune=False),
                    journal=None, preload_aot=False),
        start=False,
    )
    try:
        names = srv.register_fixture("warmup", genes=genes,
                                     modules=modules, n_samples=samples,
                                     seed=seed)
        d = srv._dataset("warmup", names["discovery"])
        t = srv._dataset("warmup", names["test"])
        plan = srv._build_plan(d, t, None, n_perm=n_perm, seed=0,
                               alternative="greater", adaptive=False,
                               rule=None)
        plan.base = 0
        return srv._pack_engine(d, t, [plan]), plan.n_perm
    finally:
        srv.close(drain=False)


def _direct_engine(genes: int, modules: int, samples: int, seed: int,
                   chunk: int):
    """The engine a direct ``module_preservation`` call for this fixture
    builds (mesh-free, replicated): same ``_overlap_setup``, same
    constructor, same config defaults."""
    from .models.preservation import _overlap_setup
    from .parallel.engine import PermutationEngine
    from .utils.config import EngineConfig
    from . import data as dmod  # noqa: F401  (fixture import path parity)
    from .models import dataset as ds

    mixed, assign = _fixture(genes, modules, samples, seed)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    built = ds.build_datasets(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td},
    )
    norm = ds.normalize_module_assignments(assign, built, ["d"])["d"]
    _labels, specs, _counts, pool = _overlap_setup(
        built["d"], built["t"], norm, None, "0", "overlap"
    )
    return PermutationEngine(
        built["d"].correlation, built["d"].network, built["d"].data,
        built["t"].correlation, built["t"].network, built["t"].data,
        specs, pool, config=EngineConfig(chunk_size=chunk),
    )


def parse_grid(spec: str | None, genes: int, modules: int,
               samples: int) -> list[tuple[int, int, int]]:
    """``--grid "300:6:24,600:10:24"`` → shape triples; None → the single
    shape from the scalar flags."""
    if not spec:
        return [(genes, modules, samples)]
    out = []
    for part in spec.split(","):
        g, m, s = (int(x) for x in part.strip().split(":"))
        out.append((g, m, s))
    return out


def warmup_grid(shapes, chunk: int, n_perm: int | None,
                fixture_seed: int = 7, target: str = "both",
                telemetry=None) -> dict:
    """Export the program grid for every shape; returns the per-shape,
    per-target ``{program: source}`` report plus store stats. Wrapped in
    a ``warmup_start``/``warmup_end`` span when a telemetry bus is
    active."""
    from .utils import aot
    from .utils import telemetry as tm

    store = aot.get_store()
    tel, owned = tm.resolve_arg(telemetry)
    sid = None
    if tel is not None:
        sid = tel.begin_span("warmup_start", shapes=len(shapes),
                             chunk=int(chunk), target=target)
    t0 = time.perf_counter()
    report: dict = {"shapes": [], "chunk": int(chunk), "target": target}
    try:
        for genes, modules, samples in shapes:
            row: dict = {"genes": genes, "modules": modules,
                         "samples": samples}
            if target in ("serve", "both"):
                eng, np_this = _serve_engine(
                    genes, modules, samples, fixture_seed, chunk, n_perm
                )
                row["serve"] = eng.warmup_export(np_this)
                eng.release()
            if target in ("direct", "both"):
                eng = _direct_engine(genes, modules, samples,
                                     fixture_seed, chunk)
                row["direct"] = eng.warmup_export(n_perm or 0)
                eng.release()
            report["shapes"].append(row)
    finally:
        report["s"] = round(time.perf_counter() - t0, 3)
        if store is not None:
            report["store"] = store.stats()
        if tel is not None:
            tel.end_span(sid, "warmup_end", s=report["s"],
                         shapes=len(report["shapes"]))
            if owned:
                tel.close()
    return report


def measure_first_run(genes: int, modules: int, samples: int,
                      fixture_seed: int, chunk: int,
                      n_perm: int) -> dict:
    """The warm-start proof measurement: build the serve-path engine for
    this shape IN THIS PROCESS (run it fresh for an honest cold/warm
    number), run one fixed-n null under a private telemetry bus, and
    report the run's ``compile_span`` estimate, its acquisition source,
    and the wall/steady throughput."""
    from .utils import telemetry as tm

    eng, _ = _serve_engine(genes, modules, samples, fixture_seed, chunk,
                           n_perm)
    fd, tel_path = tempfile.mkstemp(suffix=".jsonl",
                                    prefix="netrep_warmup_")
    os.close(fd)
    try:
        tel, _owned = tm.resolve_arg(tel_path)
        t0 = time.perf_counter()
        try:
            _nulls, completed = eng.run_null(
                n_perm, key=[0], telemetry=tel
            )
        finally:
            tel.close()
        wall = time.perf_counter() - t0
        compile_s, source = None, None
        with open(tel_path, encoding="utf-8") as f:
            for line in f:
                if '"compile_span"' not in line:
                    continue
                e = json.loads(line)
                if e.get("ev") == "compile_span":
                    compile_s = float(e["data"].get("s", 0.0))
                    source = e["data"].get("source")
        return {
            "genes": int(genes), "modules": int(modules),
            "samples": int(samples), "chunk": int(chunk),
            "n_perm": int(n_perm), "completed": int(completed),
            "first_run_s": round(wall, 3),
            "compile_span_s": (round(compile_s, 4)
                               if compile_s is not None else None),
            "source": source,
            "perms_per_sec": round(completed / wall, 2) if wall > 0 else 0,
        }
    finally:
        eng.release()
        try:
            os.unlink(tel_path)
        except OSError:
            pass


def main_warmup(args) -> int:
    """CLI entry (dispatched from ``__main__``): export the grid, or
    ``--measure`` the first-run compile span for the shape."""
    from .utils import aot

    if args.store:
        os.environ[aot.STORE_ENV] = args.store
        aot.reset_store()
    if args.measure:
        out = measure_first_run(args.genes, args.modules, args.samples,
                                args.fixture_seed, args.chunk,
                                args.n_perm or 256)
        print(json.dumps(out) if args.json else (
            f"first run {out['first_run_s']}s, compile_span "
            f"{out['compile_span_s']}s (source: {out['source']}), "
            f"{out['perms_per_sec']} perms/s"
        ))
        return 0
    shapes = parse_grid(args.grid, args.genes, args.modules, args.samples)
    report = warmup_grid(shapes, args.chunk, args.n_perm,
                         fixture_seed=args.fixture_seed,
                         target=args.target, telemetry=args.telemetry)
    if args.json:
        print(json.dumps(report))
    else:
        for row in report["shapes"]:
            for tgt in ("serve", "direct"):
                if tgt in row:
                    progs = ", ".join(
                        f"{k}={v}" for k, v in row[tgt].items()
                    )
                    print(f"{row['genes']}g/{row['modules']}m/"
                          f"{row['samples']}s [{tgt}]: {progs}")
        st = report.get("store") or {}
        print(f"warmup done in {report['s']}s: "
              f"{st.get('entries', 0)} store entries "
              f"({st.get('bytes', 0)} bytes), "
              f"{st.get('exports', 0)} exported this run")
    return 0
