"""Pinned anomaly-detector registry (ISSUE 20).

Every signal in this registry was already being computed somewhere in the
repo — and then merely logged: the stall watchdog's warn→act escalation,
the fault ladder's rung climbs (``device_lost``, ``degraded_to_cpu``),
SLO burn past budget, brownout entry, replica failover and eviction, the
perf-ledger and roofline drift verdicts, checkpoint and AOT-store
refusals. This module unifies them: each firing detector emits ONE
``anomaly_detected`` event (carrying ``detector=<name>``), warns via the
package logger, and — when ``NETREP_BUNDLE_DIR`` names a directory —
triggers a diagnostic bundle (:mod:`netrep_tpu.utils.bundle`), rate-
limited per detector so an anomaly storm cannot fill a disk.

Two trigger paths feed :func:`fire`:

- **event-mapped** (:data:`EVENT_DETECTORS`): anomalies that already ARE
  telemetry events are picked up by :func:`scan`, which the flight
  observer calls with every emitted record — no call-site changes needed;
- **site-fired**: anomalies computed outside the event stream (drift
  check verdicts, refusal raises, escalation decisions) call
  :func:`fire` directly at the site that computes the verdict.

The ``anomaly_detected`` event is emitted on the bus that carried (or
observed) the triggering signal, so a user run's JSONL tells its own
anomaly story and the ``--recovery`` timeline renders the detector label
inline; the flight ring sees it either way.

``DETECTORS`` is pinned: the bundle report, the watcher's anomalies
section, and tests key on these names. Adding a detector is additive;
renaming or removing one is a breaking schema change.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from . import telemetry as tm

logger = logging.getLogger("netrep_tpu")

#: the complete pinned catalogue of anomaly detectors
DETECTORS = (
    "stall_escalation",    # watchdog warn→act: hung dispatch abandoned
    "device_lost",         # fault ladder: a device (or tunnel) died
    "degraded_to_cpu",     # fault ladder: run continued on CPU fallback
    "slo_burn",            # serve: tenant burn rate exceeded its budget
    "brownout",            # serve: scheduler entered brownout shedding
    "replica_failover",    # fleet: unnoticed replica loss → failover
    "replica_evicted",     # fleet: noticed eviction → handoff
    "perf_drift",          # perf --check: throughput regressed vs history
    "roofline_drift",      # roofline --check: utilisation regressed
    "checkpoint_refused",  # checkpoint: resume refused (identity mismatch)
    "aot_refused",         # AOT store: entry quarantined as unusable
)

#: telemetry event name → detector, for anomalies that already ride the
#: bus as first-class events (the scan path)
EVENT_DETECTORS = {
    "device_lost": "device_lost",
    "degraded_to_cpu": "degraded_to_cpu",
    "serve_brownout_enter": "brownout",
    "replica_lost": "replica_failover",
    "evict_notice": "replica_evicted",
}

#: auto-bundle opt-in: when set, a firing detector collects a diagnostic
#: bundle under this directory (rate-limited per detector)
BUNDLE_DIR_ENV = "NETREP_BUNDLE_DIR"

#: minimum seconds between auto-collected bundles for the SAME detector —
#: an anomaly storm (e.g. a retry loop of device losses) yields one
#: bundle, not one per event
COOLDOWN_S = 60.0

_lock = threading.Lock()
_last_bundle: dict[str, float] = {}


def scan(bus, record: dict) -> None:
    """Event-mapped detection: called by the flight observer with every
    emitted record on any bus. Forensic events are ignored (a detector
    must never re-trigger off its own output), everything else is matched
    against :data:`EVENT_DETECTORS`."""
    ev = record.get("ev")
    if ev in tm.FORENSIC_EVENTS:
        return
    name = EVENT_DETECTORS.get(ev)
    if name is None:
        return
    data = record.get("data") or {}
    info = {
        k: v for k, v in data.items()
        if k not in ("span", "parent")
        and isinstance(v, (str, int, float, bool))
    }
    fire(name, telemetry=bus, **info)


def fire(name: str, telemetry=None, **data) -> str | None:
    """Fire one pinned detector: emit ``anomaly_detected`` (on the given
    bus, else the ambient one — the flight ring sees it either way), warn
    via the package logger, and auto-collect a diagnostic bundle when
    ``NETREP_BUNDLE_DIR`` is set. Returns the bundle path when one was
    written, else None."""
    if name not in DETECTORS:
        raise ValueError(f"unknown detector {name!r}; pinned: {DETECTORS}")
    tel = tm.resolve(telemetry)
    if tel is not None:
        tel.emit("anomaly_detected", detector=name, **data)
    logger.warning(
        "anomaly detected [%s]%s", name,
        (": " + " ".join(f"{k}={v}" for k, v in sorted(data.items()))
         if data else ""),
    )
    return maybe_bundle(name, telemetry=tel)


def maybe_bundle(name: str, telemetry=None,
                 clock=time.monotonic) -> str | None:
    """Auto-collect a bundle for detector ``name`` if enabled and out of
    cooldown. Best-effort: a collection failure warns, never raises."""
    root = os.environ.get(BUNDLE_DIR_ENV)
    if not root:
        return None
    now = clock()
    with _lock:
        last = _last_bundle.get(name)
        if last is not None and now - last < COOLDOWN_S:
            return None
        _last_bundle[name] = now
    from . import bundle

    try:
        return bundle.collect(
            os.path.join(root, f"netrep-bundle-{name}"),
            reason=name, telemetry=telemetry,
        )
    # netrep: allow(exception-taxonomy) — auto-collection is best-effort forensics; a bundle failure must never turn an anomaly into a crash
    except Exception:
        logger.warning("diagnostic bundle collection for %r failed",
                       name, exc_info=True)
        return None


def reset() -> None:
    """Forget per-detector cooldown state (tests)."""
    with _lock:
        _last_bundle.clear()
