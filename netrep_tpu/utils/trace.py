"""Offline span-tree reconstruction + Chrome/Perfetto export (ISSUE 5).

The telemetry JSONL is a flat event stream; the span fields
(:mod:`netrep_tpu.utils.telemetry` — additive ``data["span"]`` /
``data["parent"]``) give it causal structure. This module rebuilds the
tree offline and renders it two ways, touching no backend (usable on a
box whose tunnel is dead, like the rest of the ``telemetry`` CLI):

- :func:`render_perfetto` — Chrome trace-event JSON
  (``python -m netrep_tpu telemetry run.jsonl --trace out.json``): open
  it in Perfetto / ``chrome://tracing``. Spans are complete (``"X"``)
  events with µs ``ts``/``dur``; one ``pid`` per run id; ``tid`` is the
  span's tree depth, so overlapping levels (a double-buffered dispatch
  issued inside the previous chunk's window) land on separate rows
  instead of mis-nesting.
- :func:`time_split` — the compile / dispatch / transfer / host wall-time
  attribution of each null run, defined to sum to the run span exactly:
  ``dispatch`` is the measured in-dispatch host time minus the estimated
  ``compile_span`` carve-out, ``transfer`` the measured device→host pull
  time, and ``host`` the remainder (python loop, monitor folds,
  checkpoint writes).

Span pairing rule (one rule, shared with the emitters): all events
carrying the same ``data["span"]`` id form one span; the last of them
with a numeric ``s`` closes it (``t_start = t - s``), the others are
begin/annotation markers. A timed event with ``parent`` but no ``span``
(e.g. per-chunk ``dispatch``) is a leaf span of its own; an untimed one
is an instant attached to its parent.
"""

from __future__ import annotations

import json
from typing import Iterable

from .telemetry import read_events

#: events whose duration is an end-of-run estimate, not an in-place
#: measurement: the exporter renders them at their PARENT span's start
#: (compile happens first), since their emit time is the run's end
_AT_PARENT_START = frozenset({"compile_span"})

_META_KEYS = ("span", "parent", "s")


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def build_span_tree(events: Iterable[dict]) -> tuple[dict, list]:
    """Fold an event stream into ``(spans, instants)``.

    ``spans`` maps span id → node dict with keys ``id``, ``name``,
    ``parent`` (id or None), ``t_start``/``t_end`` (wall seconds),
    ``dur_s``, ``run``, ``args`` (merged non-meta data fields),
    ``children`` (ids, file order), ``depth`` (1-based; roots are 1).
    Timed leaf events without an id get synthetic ``e<n>`` ids.
    ``instants`` is a list of ``{"name", "t", "parent", "run", "args"}``
    for untimed point events. Unknown parent references are kept verbatim
    (the node simply becomes a root) — a crashed run must still render.
    """
    groups: dict[str, list[dict]] = {}
    order: list[str] = []
    leaves: list[dict] = []
    instants: list[dict] = []
    for i, e in enumerate(events):
        d = e.get("data") or {}
        sid = d.get("span")
        if isinstance(sid, str) and sid:
            if sid not in groups:
                groups[sid] = []
                order.append(sid)
            groups[sid].append(e)
        elif _is_num(d.get("s")):
            leaves.append((f"e{i}", e))
        else:
            instants.append({
                "name": e["ev"],
                "t": e.get("t"),
                "parent": d.get("parent"),
                "run": e.get("run"),
                "args": {k: v for k, v in d.items() if k not in _META_KEYS},
            })

    spans: dict[str, dict] = {}
    for sid in order:
        evs = groups[sid]
        closing = None
        for e in evs:
            if _is_num((e.get("data") or {}).get("s")):
                closing = e
        name = (closing or evs[0])["ev"]
        for suffix in ("_end", "_start"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        parent = None
        for e in evs:
            p = (e.get("data") or {}).get("parent")
            if p is not None:
                parent = p
                break
        args: dict = {}
        for e in evs:
            for k, v in (e.get("data") or {}).items():
                if k not in _META_KEYS:
                    args.setdefault(k, v)
        if closing is not None:
            dur = float(closing["data"]["s"])
            t_end = float(closing.get("t") or 0.0)
            t_start = t_end - dur
        else:  # begin-only span (crashed / still running): zero width
            dur = 0.0
            t_start = t_end = float(evs[0].get("t") or 0.0)
        spans[sid] = {
            "id": sid, "name": name, "parent": parent,
            "t_start": t_start, "t_end": t_end, "dur_s": dur,
            "run": (closing or evs[0]).get("run"),
            "args": args, "children": [],
        }
    for eid, e in leaves:
        d = e["data"]
        dur = float(d["s"])
        t_end = float(e.get("t") or 0.0)
        spans[eid] = {
            "id": eid, "name": e["ev"], "parent": d.get("parent"),
            "t_start": t_end - dur, "t_end": t_end, "dur_s": dur,
            "run": e.get("run"),
            "args": {k: v for k, v in d.items() if k not in _META_KEYS},
            "children": [],
        }
    for sid, node in spans.items():
        p = node["parent"]
        if p in spans:
            spans[p]["children"].append(sid)

    def depth(sid, seen=()):
        node = spans[sid]
        if "depth" in node:
            return node["depth"]
        p = node["parent"]
        d = 1 if (p not in spans or p in seen) else depth(p, seen + (sid,)) + 1
        node["depth"] = d
        return d

    for sid in spans:
        depth(sid)

    # distributed-trace propagation (ISSUE 13): a span carrying a
    # ``trace`` arg (the client-minted W3C-style trace id the scheduler
    # stamps on request spans) gives it to every descendant that lacks
    # one — so the whole per-request subtree (request_packed,
    # request_cost, request_done) is findable by the caller's trace id,
    # and the merged multi-file export below can group one request's
    # spans across process generations under one pid.
    def inherit_trace(sid, seen=()):
        node = spans[sid]
        tr = node["args"].get("trace")
        if tr is not None:
            return tr
        p = node["parent"]
        if p in spans and p not in seen:
            tr = inherit_trace(p, seen + (sid,))
            if tr is not None:
                node["args"]["trace"] = tr
        return tr

    for sid in spans:
        inherit_trace(sid)
    return spans, instants


def build_span_tree_file(path: str) -> tuple[dict, list]:
    return build_span_tree(read_events(path))


def merge_events(paths) -> list[dict]:
    """Concatenate the event streams of several telemetry JSONL files
    into one (file order, then line order), namespacing every span id and
    parent reference by its run id (``<run>:<sid>``) so the per-bus
    ``s<n>`` counters of independent processes — a client-side log plus N
    server generations of a ``--recover`` lineage — can never collide.
    Cross-process causality is carried by the ``trace`` ids the serve
    layer stamps on request spans (:func:`build_span_tree` propagates
    them down each subtree), so a SIGKILL + ``--recover`` run renders as
    ONE continuous trace keyed by the client-minted id."""
    out = []
    for path in paths:
        for e in read_events(path):
            run = e.get("run")
            d = e.get("data") or {}
            if run and (d.get("span") or d.get("parent")):
                d = dict(d)
                for k in ("span", "parent"):
                    v = d.get(k)
                    if isinstance(v, str) and v and ":" not in v:
                        d[k] = f"{run}:{v}"
                e = {**e, "data": d}
            out.append(e)
    return out


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace-event export
# ---------------------------------------------------------------------------


def render_perfetto(events: Iterable[dict]) -> dict:
    """Chrome trace-event JSON (``{"traceEvents": [...]}``) from the span
    tree. Deterministic: stable key order per event (name, ph, ts, dur,
    pid, tid, args), µs integer timestamps relative to the earliest event,
    pids assigned per run id in first-appearance order, tid = span depth.
    Instant events ride as thread-scoped ``"i"`` marks on their parent's
    row. Purely offline — no backend is touched."""
    events = list(events)
    spans, instants = build_span_tree(events)
    runs: list[str] = []
    for e in events:
        r = e.get("run")
        if r is not None and r not in runs:
            runs.append(r)
    pid_of = {r: i + 1 for i, r in enumerate(runs)}
    # distributed traces (ISSUE 13): spans carrying a (propagated)
    # ``trace`` id group under one pid PER TRACE ID, appended after the
    # run pids — so a request's subtree renders as one continuous track
    # even when its spans came from several processes / server
    # generations. Logs without trace ids render exactly as before.
    traces: list[str] = []
    for n in spans.values():
        tr = n["args"].get("trace")
        if isinstance(tr, str) and tr and tr not in traces:
            traces.append(tr)
    trace_pid = {tr: len(runs) + i + 1 for i, tr in enumerate(traces)}

    def span_pid(n: dict) -> int:
        tr = n["args"].get("trace")
        if isinstance(tr, str) and tr in trace_pid:
            return trace_pid[tr]
        return pid_of.get(n["run"], 1)

    ts = [n["t_start"] for n in spans.values()]
    ts += [i["t"] for i in instants if i["t"] is not None]
    ts += [float(e["t"]) for e in events if e.get("t") is not None]
    t_base = min(ts) if ts else 0.0

    def us(t: float) -> int:
        return int(round((t - t_base) * 1e6))

    out = []
    for r in runs:
        out.append({
            "name": "process_name", "ph": "M", "pid": pid_of[r],
            "args": {"name": f"run {r}"},
        })
    for tr in traces:
        out.append({
            "name": "process_name", "ph": "M", "pid": trace_pid[tr],
            "args": {"name": f"trace {tr[:16]}"},
        })
    depths = sorted({
        (span_pid(n), n["depth"]) for n in spans.values()
    })
    for pid, d in depths:
        out.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": d,
            "args": {"name": f"span depth {d}"},
        })
    rows = []
    for sid, n in spans.items():
        t0 = n["t_start"]
        if n["name"] in _AT_PARENT_START and n["parent"] in spans:
            t0 = spans[n["parent"]]["t_start"]
        rows.append({
            "name": n["name"], "ph": "X", "ts": us(t0),
            "dur": int(round(n["dur_s"] * 1e6)),
            "pid": span_pid(n), "tid": n["depth"],
            "args": {**n["args"], "span": sid},
        })
    for i in instants:
        parent_depth = (
            spans[i["parent"]]["depth"] if i["parent"] in spans else 0
        )
        rows.append({
            "name": i["name"], "ph": "i",
            "ts": us(i["t"] if i["t"] is not None else t_base),
            "pid": pid_of.get(i["run"], 1), "tid": parent_depth + 1,
            "s": "t", "args": i["args"],
        })
    rows.sort(key=lambda r: (r["ts"], r["pid"], r["tid"], r["name"]))
    return {"traceEvents": out + rows, "displayTimeUnit": "ms"}


def write_perfetto(path, out_path: str) -> int:
    """File(s) → file export; returns the number of trace events written.
    ``path`` may be a single JSONL path or a list of them — several files
    merge via :func:`merge_events` (run-namespaced span ids, one pid per
    trace id), so a client log + the pre- and post-crash server logs of a
    ``--recover`` lineage export as one continuous trace."""
    if isinstance(path, (list, tuple)):
        events = (merge_events(path) if len(path) > 1
                  else list(read_events(path[0])))
    else:
        events = list(read_events(path))
    trace = render_perfetto(events)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    return len(trace["traceEvents"])


# ---------------------------------------------------------------------------
# compile / dispatch / transfer / host time split
# ---------------------------------------------------------------------------


def time_split(events: Iterable[dict]) -> dict | None:
    """Wall-time attribution over every null run in the stream, defined so
    the four components sum to the run-span total *exactly*:

    - ``compile_s``  — the loops' end-of-run first-interval estimate
      (``compile_span`` events), clamped into the measured dispatch time
      it is a carve-out of;
    - ``rescue_s``   — the screened null loops' f32 rescue re-dispatches
      (``rescue_dispatch`` events, ISSUE 16), carved out of the dispatch
      time they run inside;
    - ``dispatch_s`` — measured host time inside chunk/superchunk
      dispatches (key derivation + program launch; on synchronous
      backends this includes device compute), minus the compile and
      rescue carve-outs;
    - ``transfer_s`` — measured device→host pull time (chunk writes /
      tally pulls; on async backends this includes the device drain);
    - ``host_s``     — the remainder: python loop, monitor folds,
      checkpoint writes, progress callbacks.

    ``compile_by_src`` splits the raw compile estimate by each
    ``compile_span`` event's acquisition ``source`` (``aot``/``jit``/
    ``memo``, ISSUE 15) — warm and cold compile history never mix in the
    report (events predating the tag count as ``jit``).

    Returns None when the stream has no closed null run."""
    total = dispatch_raw = transfer = compile_raw = rescue_raw = 0.0
    n_runs = 0
    by_src: dict[str, float] = {}
    for e in events:
        d = e.get("data") or {}
        if e["ev"] == "null_run_end" and _is_num(d.get("s")):
            total += float(d["s"])
            n_runs += 1
        elif e["ev"] == "dispatch" and _is_num(d.get("s")):
            dispatch_raw += float(d["s"])
        elif e["ev"] == "rescue_dispatch" and _is_num(d.get("s")):
            rescue_raw += float(d["s"])
        elif e["ev"] == "compile_span" and _is_num(d.get("s")):
            compile_raw += float(d["s"])
            src = str(d.get("source") or "jit")
            by_src[src] = by_src.get(src, 0.0) + float(d["s"])
        if _is_num(d.get("transfer_s")):
            transfer += float(d["transfer_s"])
    if not n_runs:
        return None
    compile_s = min(compile_raw, dispatch_raw)
    rescue_s = min(rescue_raw, dispatch_raw - compile_s)
    host = max(total - dispatch_raw - transfer, 0.0)
    return {
        "n_runs": n_runs,
        "total_s": total,
        "compile_s": compile_s,
        "rescue_s": rescue_s,
        "dispatch_s": dispatch_raw - compile_s - rescue_s,
        "transfer_s": transfer,
        "host_s": host,
        "compile_by_src": by_src,
    }


def render_time_split(path: str) -> str:
    """Human rendering of :func:`time_split` for the ``telemetry`` CLI
    report; empty string when the log holds no closed null run."""
    split = time_split(read_events(path))
    if split is None:
        return ""
    total = split["total_s"] or 1.0
    lines = [
        f"time split over {split['n_runs']} null run(s) "
        f"({split['total_s']:.3f}s total):"
    ]
    for k in ("compile_s", "rescue_s", "dispatch_s", "transfer_s",
              "host_s"):
        src = ""
        if k == "compile_s" and split.get("compile_by_src"):
            # the src column (ISSUE 15): where each run's compile estimate
            # came from — `jit` compiled cold, `aot` deserialized from the
            # warm-start store, `memo` reused in-process
            src = "  src: " + " ".join(
                f"{s}={v:.3f}s"
                for s, v in sorted(split["compile_by_src"].items())
            )
        lines.append(
            f"  {k[:-2]:<9} {split[k]:>10.3f}s  "
            f"{100.0 * split[k] / total:5.1f}%{src}"
        )
    return "\n".join(lines)
