"""Unified run telemetry: structured event bus + metrics registry (ISSUE 3).

The rebuild runs hour-scale permutation nulls on flaky tunneled TPU
backends whose dominant failures are *silent* — a dead axon tunnel hangs
``jax.devices()`` mid-run, a probe race drops the run onto CPU unannounced
— and until now instrumentation was scattered across ``NullProfile``,
``PairTimer``, the progress printer, and the autotune cache with no common
schema. This module is the one structured record of what a run did:

- :class:`Telemetry` — a run-scoped event bus with a crash-safe
  append-only JSONL sink (one flushed line per event; a crash loses at
  most the in-flight line) and an in-memory :class:`MetricsRegistry`
  folded from the same events, so the live view and an offline
  aggregation of the file can never disagree.
- :class:`MetricsRegistry` — counters, gauges, and histogram summaries
  derived deterministically from the event stream (see
  :meth:`MetricsRegistry.fold`), with a human summary table
  (:meth:`~MetricsRegistry.render_summary`) and a Prometheus-style text
  exposition (:meth:`~MetricsRegistry.render_prometheus`) for the
  ``benchmarks/tpu_watch.sh`` loop.
- :class:`StallWatchdog` — a monotonic-clock heartbeat armed per null
  run: when no chunk completes within ``factor``× the *measured*
  steady-state chunk time it emits a ``stall_suspected`` event and warns
  once via the ``netrep_tpu`` logger — the exact dead-tunnel failure mode
  ``utils/backend.py`` documents (the dial hangs instead of erroring).
- ambient activation (:meth:`Telemetry.activate` / :func:`current`) so
  leaf modules (checkpoint, backend, autotune, distributed) can emit
  without threading a handle through every signature.

Event schema (version :data:`SCHEMA_VERSION`), one JSON object per line::

    {"v": 1, "t": <unix seconds>, "m": <monotonic seconds>,
     "run": "<run id>", "ev": "<event name>", "data": {...}}

Exactly these six keys, in this order (:data:`EVENT_KEYS`) — pinned by the
schema-stability test so downstream parsers (``summarize_watch.py``,
dashboards) never break silently. ``data`` values are JSON scalars/lists;
numeric fields fold into the registry by one rule (``fold``).

Hierarchical spans (ISSUE 5) ride the same schema as *additive* ``data``
fields — the six top-level keys never change:

- ``data["span"]`` — the event belongs to span ``span`` (a run-unique
  deterministic id ``s<n>``); a begin event carries it without ``s``, the
  closing event carries it with the measured ``s`` duration.
- ``data["parent"]`` — the id of the enclosing span. :meth:`Telemetry.emit`
  attaches it automatically from the ambient span stack when the caller
  passes neither ``span`` nor ``parent``, so leaf events (retries, stalls,
  checkpoint saves) land under whatever span was open when they fired.

The stack is maintained by :meth:`Telemetry.span` (context-manager spans),
:meth:`Telemetry.begin_span`/:meth:`Telemetry.end_span` (loop-shaped spans
whose begin and end are separate events, e.g. ``null_run_start`` /
``null_run_end``), and :meth:`Telemetry.pushed` (adopt an externally
allocated id for a dynamic extent — how chunk dispatches parent their
retries). Span ids are a per-bus counter, so a deterministic run produces
a deterministic tree. ``netrep_tpu/utils/trace.py`` rebuilds the tree
offline and exports Chrome/Perfetto trace JSON
(``python -m netrep_tpu telemetry run.jsonl --trace out.json``).

Telemetry is OFF by default. When disabled the hot loops pay a single
``None`` check per run (not per chunk) and results are bit-identical —
telemetry only ever observes.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
import uuid
from typing import Callable, Iterable, Iterator

logger = logging.getLogger("netrep_tpu")

#: version of the event line shape; bump when keys or their meaning change
SCHEMA_VERSION = 1

#: exact top-level keys of every event line, in serialization order
EVENT_KEYS = ("v", "t", "m", "run", "ev", "data")

#: numeric data fields that accumulate (counters); every other numeric
#: field is a gauge (last value wins) unless it times something (``s`` /
#: ``*_s`` suffix → histogram). One rule, shared by the live registry and
#: the offline aggregator, so the two views cannot drift.
_SUM_FIELDS = frozenset({
    "dispatches", "host_bytes", "perms", "take", "bytes", "n_retired",
    "bytes_to_host",
})

#: recovery-path event names (ISSUE 4 fault tolerance + the backends'
#: fallback/stall events) — the set the CLI report surfaces as a dedicated
#: "recovery" section and ``--recovery`` renders as a timeline. Names are
#: pinned by tests/test_telemetry.py: downstream dashboards key on them.
RECOVERY_EVENTS = (
    "fault_injected",
    "retry_attempt",
    "chunk_abandoned",
    "stall_suspected",
    "stall_recovered",
    "device_lost",
    "mesh_shrunk",
    "mesh_grown",
    "degraded_to_cpu",
    "checkpoint_async_flush",
    "fingerprint_degraded_accept",
    "backend_fallback",
    "distributed_autodetect_failed",
)

#: serving-path event names (ISSUE 7 `netrep serve`) — the per-request
#: lifecycle the scheduler emits, each carrying a ``tenant`` label in
#: ``data`` (ADDITIVE fields only; schema v1 unchanged). Names are pinned
#: by tests/test_telemetry.py beside :data:`RECOVERY_EVENTS`: the CLI's
#: per-tenant section and serving dashboards key on them.
#: ``request_received`` opens the request span (``data["span"]``) and
#: ``request_done`` closes it with the request's total latency as ``s``,
#: so the trace tree shows queue wait + execution per request nested
#: under the server-lifetime ``serve_start``/``serve_end`` span.
SERVE_EVENTS = (
    "request_received",
    "request_packed",
    "request_done",
    "request_rejected",
    # crash-safe serving (ISSUE 10): deadline enforcement, idempotency
    # dedup, brownout load shedding, journal replay, wire hardening —
    # names pinned beside the PR 7 lifecycle because the recovery drill
    # and serving dashboards key on them
    "request_expired",
    "request_deduped",
    "serve_brownout_enter",
    "serve_brownout_exit",
    "journal_replayed",
    "request_malformed",
    # deadline-driven retirement re-bucketing (ISSUE 10) — was emitted
    # but never registered; ISSUE 12's telemetry-registry lint rule
    # caught the drift and pinned it here
    "request_requeued",
    # deterministic per-request cost attribution (ISSUE 13): one event
    # per served request, emitted by the scheduler after its pack
    # completes, carrying the request's exact share of the pack's
    # measured costs (``device_s``/``transfer_s``/``perms``/
    # ``bytes_to_host``/``compile_s_amortized``) split by live-module ×
    # permutation weight at every chunk — the conservation contract
    # (member costs sum bit-exactly to the pack totals) is pinned in
    # tests/test_serve_cost.py. Carries ``tenant`` + the request's
    # ``trace`` id, so a trace tells the whole cost story end to end.
    "request_cost",
)

#: fleet-serving event names (ISSUE 14 ``serve --fleet``) — the replica
#: lifecycle the coordinator emits, each carrying a ``replica`` label in
#: ``data``. Pinned beside :data:`SERVE_EVENTS` for the same reason: the
#: CLI's per-replica section, ``chaos --fleet``'s timeline, and fleet
#: dashboards key on these names, and the ``telemetry-registry`` lint
#: rule enforces membership statically.
FLEET_EVENTS = (
    #: a replica entered the hash ring (boot, join, or respawn)
    "replica_joined",
    #: the health loop declared a replica dead (missed heartbeats /
    #: worker exit) — always followed by a failover pair
    "replica_lost",
    #: the journal shipper moved newly-fsynced records to the designated
    #: peer's copy and advanced the acked offset
    "journal_shipped",
    #: failover began: the dead replica's shipped journal is about to be
    #: replayed into its peer (``failover_done.s`` = the measured
    #: failover time the drill and the ``--recovery`` timeline report)
    "failover_start",
    "failover_done",
    #: the consistent-hash ring changed (join or leave): placement moved
    #: for the departed/arrived replica's keys ONLY — never a recompute
    "ring_rebalanced",
    # -- replica lifecycle + autoscaling (ISSUE 19) ---------------------
    #: one event per lifecycle state-machine transition
    #: (``serve/lifecycle.py``): ``prev``/``to``/``gen``/``reason``
    #: beside the ``replica`` label — the machine renders straight off
    #: the event stream
    "replica_state",
    #: the autoscaler decided to grow the fleet (aggregate backlog-drain
    #: estimate above ``scale_up_drain_s``); ``replica`` = the spawned id
    "autoscale_up",
    #: the autoscaler drained-and-retired an idle replica
    "autoscale_down",
    #: the fleet drained to ZERO replicas — ``journal`` names the last
    #: shipped copy, the persistent state a spawn-on-demand boots from
    "scale_to_zero",
    #: a submission against an empty fleet triggered a spawn; the
    #: request queues behind the boot instead of being rejected
    "spawn_on_demand",
    #: an eviction notice arrived (wire op / ``NETREP_FLEET_EVICT``):
    #: the replica leaves the ring BEFORE the kill and hands its work
    #: off — ``grace_s`` bounds the drain
    "evict_notice",
    #: the noticed-eviction handoff completed (tail pre-shipped, peer
    #: adopted): ``s`` = measured handoff time, ``requeued``/``results``
    #: = what the peer took over — zero recompute, unlike a failover
    "evict_handoff_done",
)

#: pinned latency histogram bucket upper bounds (seconds) for the
#: per-tenant serving series (``netrep_serve_latency_seconds`` in
#: ``metrics_text()``; a final +Inf bucket is implicit). Changing these
#: re-bins every dashboard keyed on the exposition — the boundaries are
#: schema surface, pinned by tests/test_telemetry.py.
LATENCY_BUCKETS_S = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0,
)

#: pinned attributed-cost histogram bucket upper bounds (device-seconds
#: per request) for ``netrep_serve_request_device_seconds`` — same
#: pinning contract as :data:`LATENCY_BUCKETS_S`
COST_BUCKETS_S = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
)


class BucketHistogram:
    """Fixed-boundary cumulative-style histogram (the Prometheus shape):
    per-bucket counts over pinned upper bounds plus a +Inf overflow
    bucket, with count/sum and a quantile estimator — the p50/p99 the
    serve plane's ops surface reports without storing every sample.

    Quantiles interpolate linearly inside the winning bucket (0 as the
    lower edge of the first), the standard Prometheus
    ``histogram_quantile`` convention — an estimate bounded by the pinned
    boundaries, not an exact order statistic."""

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError("bucket boundaries must be ascending")
        self.counts = [0] * (len(self.buckets) + 1)   # +Inf last
        self.n = 0
        self.total = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        self.n += 1
        self.total += v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0..1), or None for an empty histogram.
        The +Inf bucket degrades to the last finite boundary — a bounded
        answer beats an unbounded guess on an ops dashboard."""
        if self.n == 0:
            return None
        rank = q * self.n
        cum = 0
        for i, c in enumerate(self.counts):
            prev = cum
            cum += c
            if cum >= rank and c:
                if i >= len(self.buckets):
                    return self.buckets[-1] if self.buckets else 0.0
                lo = self.buckets[i - 1] if i else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * ((rank - prev) / c)
        return self.buckets[-1] if self.buckets else 0.0

    def prom_lines(self, name: str, labels: str = "") -> list[str]:
        """Prometheus histogram exposition lines (cumulative ``le``
        buckets + ``_count``/``_sum``); ``labels`` is the pre-rendered
        inner label list (e.g. ``tenant="a"``)."""
        sep = "," if labels else ""
        out = []
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(
                f'{name}_bucket{{{labels}{sep}le="{b:g}"}} {cum}'
            )
        out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {self.n}')
        out.append(f"{name}_count{{{labels}}} {self.n}")
        out.append(f"{name}_sum{{{labels}}} {self.total:g}")
        return out

    def state(self) -> dict:
        return {"buckets": list(self.buckets), "counts": list(self.counts),
                "n": self.n, "sum": self.total}

#: engine/infrastructure event names outside the recovery and serving
#: sets: the null-loop progress events, compile/autotune accounting,
#: checkpoint lifecycle, and the atlas tile plane. Together with
#: :data:`RECOVERY_EVENTS`, :data:`SERVE_EVENTS`, and :data:`SPAN_EVENTS`
#: this is the COMPLETE schema of event names the package may emit —
#: enforced statically by the ``telemetry-registry`` lint rule
#: (:mod:`netrep_tpu.analysis`): an ``emit()`` of an unregistered name is
#: a lint finding, so the schema cannot drift silently between the code
#: and the dashboards/summarizers keyed on these names.
ENGINE_EVENTS = (
    "allgather",
    "aot_export",
    "aot_load",
    "aot_store_miss",
    "autotune_hit",
    "autotune_miss",
    "autotune_record",
    "backend_probe",
    "checkpoint_saved",
    "checkpoint_resumed",
    "chunk",
    "compile_span",
    "dispatch",
    "distributed_init",
    "module_retired",
    "null_pass_end",
    "rescue_dispatch",
    "roofline",
    "superchunk",
    "tail_fit",
    "tail_trim_skipped",
    "tile",
    "tile_screen",
)

#: span begin/end event names (:meth:`Telemetry.span`,
#: :meth:`Telemetry.begin_span`/:meth:`Telemetry.end_span`) — the node
#: names of the trace tree. Pinned for the same reason as
#: :data:`ENGINE_EVENTS`: ``trace.py`` and Perfetto exports key on them.
SPAN_EVENTS = (
    "null_run_start",
    "null_run_end",
    "observed",
    "pack",
    "pair_start",
    "pair_end",
    "run_start",
    "run_end",
    "serve_start",
    "serve_end",
    "tile_pass_start",
    "tile_pass_end",
    "warmup_start",
    "warmup_end",
)

#: all-pairs grid event names (ISSUE 17 ``grid_preservation``) — the
#: atlas lifecycle: the grid span brackets the whole D×D job, each cell
#: emits start/done (``source`` says whether it was computed or answered
#: from the digest-keyed manifest), ``grid_dedup_hit`` counts
#: observed-stat/module-bucket cache hits across cells sharing a
#: discovery dataset, and ``grid_warmstart_seeded`` records a
#: recomputed cell's monitor receiving a prior run's count-space
#: tallies. Pinned beside the other registries: the CLI's grid section
#: and the watcher's grid classification key on these names, and the
#: ``telemetry-registry`` lint rule enforces membership statically.
GRID_EVENTS = (
    "grid_start",
    "grid_end",
    "grid_cell_start",
    "grid_cell_done",
    "grid_dedup_hit",
    "grid_warmstart_seeded",
)

#: incident-forensics event names (ISSUE 20 flight recorder):
#: ``anomaly_detected`` is the one event every pinned detector
#: (:data:`netrep_tpu.utils.detectors.DETECTORS`) fires, always carrying
#: a ``detector`` label; ``flightrec_dump`` marks the flight ring being
#: drained (the mark itself lands in the ring first, so a dumped ring is
#: self-describing); ``bundle_written`` records a diagnostic bundle
#: landing on disk with its ``reason`` and path. Pinned beside the other
#: registries for the same reason: the ``--recovery`` timeline, the
#: watcher's anomalies section, and the ``telemetry-registry`` lint rule
#: all key on these names.
FORENSIC_EVENTS = (
    "anomaly_detected",
    "flightrec_dump",
    "bundle_written",
)

#: the union the ``telemetry-registry`` lint rule checks literal event
#: names against — every registry above, nothing else
KNOWN_EVENTS = frozenset(
    ENGINE_EVENTS + RECOVERY_EVENTS + SERVE_EVENTS + FLEET_EVENTS
    + SPAN_EVENTS + GRID_EVENTS + FORENSIC_EVENTS
)


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class MetricsRegistry:
    """Counters / gauges / histogram summaries folded from an event
    stream. ``histograms`` keeps ``[n, total, min, max]`` per name —
    enough for mean/extremes without unbounded storage."""

    def __init__(self):
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, list[float]] = {}
        self.n_events = 0
        self.runs: set[str] = set()
        self.t_first: float | None = None
        self.t_last: float | None = None

    # -- folding -----------------------------------------------------------

    def count(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, v: float) -> None:
        self.gauges[name] = v

    def observe(self, name: str, v: float) -> None:
        h = self.histograms.get(name)
        if h is None:
            self.histograms[name] = [1, float(v), float(v), float(v)]
        else:
            h[0] += 1
            h[1] += float(v)
            h[2] = min(h[2], float(v))
            h[3] = max(h[3], float(v))

    def fold(self, ev: str, data: dict, t: float | None = None,
             run: str | None = None) -> None:
        """THE aggregation rule: event count → ``<ev>.count`` counter;
        numeric fields → ``<ev>.<field>`` histogram (``s``/``*_s``),
        counter (:data:`_SUM_FIELDS`), or gauge (everything else)."""
        self.n_events += 1
        if run:
            self.runs.add(run)
        if t is not None:
            self.t_first = t if self.t_first is None else self.t_first
            self.t_last = t
        self.count(f"{ev}.count")
        for k, v in data.items():
            if not _is_number(v):
                continue
            name = f"{ev}.{k}"
            if k == "s" or k.endswith("_s"):
                self.observe(name, v)
            elif k in _SUM_FIELDS:
                self.count(name, v)
            else:
                self.gauge(name, v)

    # -- views -------------------------------------------------------------

    def as_dict(self) -> dict:
        return {
            "n_events": self.n_events,
            "runs": sorted(self.runs),
            "span_s": (
                self.t_last - self.t_first
                if self.t_first is not None else None
            ),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {
                k: {"n": h[0], "total": h[1], "min": h[2], "max": h[3],
                    "mean": h[1] / h[0]}
                for k, h in self.histograms.items()
            },
        }

    def render_summary(self) -> str:
        """Human summary table of the aggregated run(s)."""
        out = []
        span = (
            f", span {self.t_last - self.t_first:.1f}s"
            if self.t_first is not None else ""
        )
        runs = ", ".join(sorted(self.runs)) or "-"
        out.append(f"telemetry: {self.n_events} events, run(s) {runs}{span}")
        rec = {
            ev: self.counters[f"{ev}.count"]
            for ev in RECOVERY_EVENTS if f"{ev}.count" in self.counters
        }
        if rec:
            # surface the recovery story first: a run that retried/degraded
            # its way to completion should say so before the raw counters
            out.append("recovery:")
            w = max(len(k) for k in rec)
            for k in RECOVERY_EVENTS:
                if k in rec:
                    out.append(f"  {k:<{w}}  {rec[k]:g}")
        if self.counters:
            out.append("counters:")
            w = max(len(k) for k in self.counters)
            for k in sorted(self.counters):
                v = self.counters[k]
                out.append(f"  {k:<{w}}  {v:g}")
        if self.gauges:
            out.append("gauges:")
            w = max(len(k) for k in self.gauges)
            for k in sorted(self.gauges):
                out.append(f"  {k:<{w}}  {self.gauges[k]:g}")
        if self.histograms:
            out.append("timings:")
            w = max(len(k) for k in self.histograms)
            out.append(
                f"  {'':<{w}}  {'n':>6} {'total_s':>10} {'mean_s':>10} "
                f"{'min_s':>10} {'max_s':>10}"
            )
            for k in sorted(self.histograms):
                n, tot, lo, hi = self.histograms[k]
                out.append(
                    f"  {k:<{w}}  {n:>6} {tot:>10.3f} {tot / n:>10.3f} "
                    f"{lo:>10.3f} {hi:>10.3f}"
                )
        return "\n".join(out)

    def render_prometheus(self, prefix: str = "netrep") -> str:
        """Prometheus text exposition of the registry — the scrape surface
        of the ``tpu_watch.sh`` loop (regenerated after each step)."""

        def san(name: str) -> str:
            return "".join(
                c if c.isalnum() or c == "_" else "_" for c in name
            )

        lines = []
        for k in sorted(self.counters):
            n = f"{prefix}_{san(k)}_total"
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {self.counters[k]:g}")
        for k in sorted(self.gauges):
            n = f"{prefix}_{san(k)}"
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {self.gauges[k]:g}")
        for k in sorted(self.histograms):
            cnt, tot, lo, hi = self.histograms[k]
            n = f"{prefix}_{san(k)}"
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_count {cnt:g}")
            lines.append(f"{n}_sum {tot:g}")
            lines.append(f"{n}_min {lo:g}")
            lines.append(f"{n}_max {hi:g}")
        return "\n".join(lines) + ("\n" if lines else "")


class Telemetry:
    """Run-scoped event bus: JSONL sink + live :class:`MetricsRegistry`.

    Parameters
    ----------
    path : JSONL sink path (append-only; parent dirs created), or None for
        an in-memory-only bus (registry still folds — used by tests and
        short-lived tooling).
    run_id : identity stamped on every event; defaults to a fresh 8-hex id.
    clock / wall : injectable monotonic / wall clocks (fake-clock tests).
    stall_factor / watchdog_poll_s : defaults the null loops hand to the
        :class:`StallWatchdog` they arm per run.

    Thread-safe: the watchdog thread and the main loop share the sink and
    registry under one lock. Emit failures (full disk, revoked path) warn
    once and disable the sink — telemetry must never turn a working run
    into a failing one.
    """

    def __init__(
        self,
        path: str | os.PathLike | None = None,
        run_id: str | None = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        stall_factor: float = 10.0,
        watchdog_poll_s: float = 5.0,
    ):
        self.path = os.fspath(path) if path is not None else None
        self.run_id = run_id or uuid.uuid4().hex[:8]
        self.clock = clock
        self.wall = wall
        self.stall_factor = float(stall_factor)
        self.watchdog_poll_s = float(watchdog_poll_s)
        self.metrics = MetricsRegistry()
        self._lock = threading.Lock()
        self._subscribers: list[Callable[[dict], None]] = []
        self._fh = None
        self._sink_dead = False
        # hierarchical spans (ISSUE 5): deterministic per-bus id counter +
        # the ambient span stack leaf events auto-parent against. The stack
        # is shared across threads on purpose — the watchdog thread's
        # stall events belong to whatever span the loop thread has open.
        self._span_seq = 0
        self._span_stack: list[str] = []
        self._span_t0: dict[str, float] = {}
        if self.path is not None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")

    # -- bus ---------------------------------------------------------------

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        """Register an in-process observer called with each event dict."""
        self._subscribers.append(fn)

    def emit(self, ev: str, **data) -> dict:
        """Append one event to the sink (flushed — crash loses at most the
        in-flight line), fold it into the registry, notify subscribers.

        When the caller passes neither ``span`` nor ``parent`` and a span
        is open on the ambient stack, ``data["parent"]`` is attached
        automatically — point events always land under the span that was
        live when they fired (acceptance: every chunk/dispatch/retry event
        owned by exactly one parent span)."""
        if "span" not in data and "parent" not in data:
            parent = self.current_span()
            if parent is not None:
                data["parent"] = parent
        record = {
            "v": SCHEMA_VERSION,
            "t": self.wall(),
            "m": self.clock(),
            "run": self.run_id,
            "ev": str(ev),
            "data": data,
        }
        with self._lock:
            self.metrics.fold(record["ev"], data, t=record["t"],
                              run=self.run_id)
            if self._fh is not None and not self._sink_dead:
                try:
                    self._fh.write(
                        json.dumps(record, default=_json_default) + "\n"
                    )
                    self._fh.flush()
                except (OSError, ValueError):
                    self._sink_dead = True
                    logger.warning(
                        "telemetry sink %r failed; further events are "
                        "registry-only", self.path,
                    )
        for fn in self._subscribers:
            try:
                fn(record)
            # netrep: allow(exception-taxonomy) — telemetry only observes: a raising subscriber is logged, the run continues bit-identically
            except Exception:  # observers must never break the run
                logger.warning("telemetry subscriber raised", exc_info=True)
        hook = _FLIGHT_OBSERVER
        if hook is not None:
            try:
                hook(self, record)
            # netrep: allow(exception-taxonomy) — the flight recorder only observes: a ring/detector bug must never break the run it records
            except Exception:
                logger.warning("flight observer raised", exc_info=True)
        return record

    # -- hierarchical spans (ISSUE 5) --------------------------------------

    def new_span_id(self) -> str:
        """Allocate a run-unique, deterministic span id (``s<n>``): a
        counter, not a UUID, so the same run produces the same tree —
        pinned by the fault-harness determinism test."""
        with self._lock:
            self._span_seq += 1
            return f"s{self._span_seq}"

    def current_span(self) -> str | None:
        """Innermost open span id, or None outside any span."""
        with self._lock:
            return self._span_stack[-1] if self._span_stack else None

    def _push_span(self, span_id: str) -> None:
        with self._lock:
            self._span_stack.append(span_id)

    def _pop_span(self, span_id: str) -> None:
        with self._lock:
            for i in range(len(self._span_stack) - 1, -1, -1):
                if self._span_stack[i] == span_id:
                    del self._span_stack[i]
                    break

    @contextlib.contextmanager
    def pushed(self, span_id: str):
        """Make an externally allocated span id the ambient parent for the
        block — how a chunk dispatch adopts its chunk's span so retry /
        fault / stall events emitted inside nest under that chunk."""
        self._push_span(span_id)
        try:
            yield span_id
        finally:
            self._pop_span(span_id)

    @contextlib.contextmanager
    def span(self, ev: str, **data):
        """Timed span: measures the block's duration on the monotonic
        clock and emits ``ev`` with an ``s`` field on exit (also on error,
        with ``error`` naming the exception type). The single closing
        event carries the span's id and parent, and events emitted inside
        the block auto-parent to it."""
        sid = self.new_span_id()
        parent = self.current_span()
        if parent is not None:
            data.setdefault("parent", parent)
        self._push_span(sid)
        t0 = self.clock()
        try:
            yield self
        except BaseException as e:
            self._pop_span(sid)
            self.emit(ev, s=self.clock() - t0, span=sid,
                      error=type(e).__name__, **data)
            raise
        else:
            self._pop_span(sid)
            self.emit(ev, s=self.clock() - t0, span=sid, **data)

    def begin_span(self, ev: str, **data) -> str:
        """Open a span whose begin and end are *separate events* (the loop
        shape: ``null_run_start`` … ``null_run_end``): emits ``ev`` now
        carrying the new span id (+ parent), pushes the id on the ambient
        stack, and returns it for :meth:`end_span`."""
        sid = self.new_span_id()
        parent = self.current_span()
        if parent is not None:
            data.setdefault("parent", parent)
        self.emit(ev, span=sid, **data)
        with self._lock:
            self._span_stack.append(sid)
            self._span_t0[sid] = self.clock()
        return sid

    def end_span(self, span_id: str, ev: str, **data) -> dict:
        """Close a :meth:`begin_span` span: pops it and emits the closing
        ``ev`` with the same span id. ``s`` defaults to the span's measured
        duration on this bus's clock; callers with their own timing (the
        null loops use ``perf_counter``) pass ``s=`` explicitly."""
        self._pop_span(span_id)
        with self._lock:
            t0 = self._span_t0.pop(span_id, None)
        if "s" not in data and t0 is not None:
            data["s"] = self.clock() - t0
        return self.emit(ev, span=span_id, **data)

    # -- ambient activation ------------------------------------------------

    @contextlib.contextmanager
    def activate(self):
        """Make this bus the ambient telemetry (:func:`current`) for the
        dynamic extent — how leaf modules (checkpoint/backend/autotune/
        distributed) emit without signature threading."""
        _ACTIVE.append(self)
        try:
            yield self
        finally:
            _ACTIVE.remove(self)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def _json_default(v):
    """Tolerant serialization: numpy scalars/arrays ride events as plain
    JSON numbers/lists without this module importing numpy."""
    for attr in ("item",):  # numpy scalar
        if hasattr(v, attr) and not hasattr(v, "__len__"):
            return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return str(v)


#: ambient telemetry stack (innermost active bus wins)
_ACTIVE: list[Telemetry] = []

#: process-wide flight-recorder observer (ISSUE 20): called as
#: ``hook(bus, record)`` with every event emitted on ANY bus — outside
#: the bus lock, after subscribers, exception-suppressed. One slot, not a
#: list: the flight recorder is a singleton plane, and a single slot
#: keeps the disabled path a None check.
_FLIGHT_OBSERVER = None


def set_flight_observer(fn) -> None:
    """Install (or clear, with None) the process-wide flight observer —
    the seam :mod:`netrep_tpu.utils.flightrec` captures through."""
    global _FLIGHT_OBSERVER
    _FLIGHT_OBSERVER = fn


def current() -> Telemetry | None:
    """The ambient :class:`Telemetry`, or None when telemetry is off."""
    return _ACTIVE[-1] if _ACTIVE else None


def resolve(explicit: Telemetry | None) -> Telemetry | None:
    """Explicit handle if given, else the ambient bus — resolved ONCE per
    run by the null loops, so the disabled hot path pays one check."""
    return explicit if explicit is not None else current()


#: default sink when ``telemetry=True`` is passed to the public API
DEFAULT_SINK = "netrep_telemetry.jsonl"


def resolve_arg(arg) -> tuple[Telemetry | None, bool]:
    """``telemetry=`` public-API argument → ``(bus, owned)``: None/False =
    off; True = the default sink in the CWD; a path = JSONL there; an
    existing :class:`Telemetry` passes through un-owned (the caller closes
    it). ``owned`` tells the API layer to close the bus it created."""
    if arg is None or arg is False:
        return None, False
    if isinstance(arg, Telemetry):
        return arg, False
    if arg is True:
        return Telemetry(os.path.join(os.getcwd(), DEFAULT_SINK)), True
    return Telemetry(arg), True


# ---------------------------------------------------------------------------
# Stall watchdog
# ---------------------------------------------------------------------------


class StallWatchdog:
    """Monotonic-clock heartbeat for one null run.

    The null loops :meth:`beat` once per landed chunk; the watchdog
    measures the steady-state chunk time (median inter-beat interval,
    FIRST interval excluded — it absorbs jit compilation) and, when no
    chunk lands within ``factor``× that time, emits one
    ``stall_suspected`` event and warns once via the ``netrep_tpu``
    logger. This catches the documented dead-tunnel failure mode: device
    calls block in gRPC with no deadline, so the Python loop can't notice
    — but this daemon thread can.

    ``poll_interval <= 0`` disables the thread; :meth:`poll` can then be
    driven manually (fake-clock tests). Until ``min_intervals`` steady
    intervals are measured the watchdog stays silent — it never guesses a
    baseline.

    A chunk landing after a fired stall emits ``stall_recovered`` (with
    the stalled-for duration) and RE-ARMS the warning, so a second stall
    in the same run warns again instead of staying silent after a
    one-shot warning.

    Warn → act escalation (ISSUE 4): with ``action`` set, a stall that
    outlasts ``action_factor`` × the steady chunk time invokes
    ``action()`` ONCE per stall episode from the watchdog thread — the
    fault runtime uses this to checkpoint completed work and abandon the
    hung dispatch (the loop thread is blocked inside it and cannot act).
    """

    def __init__(
        self,
        telemetry: Telemetry,
        factor: float = 10.0,
        min_intervals: int = 2,
        poll_interval: float = 5.0,
        clock: Callable[[], float] | None = None,
        action: Callable[[], None] | None = None,
        action_factor: float | None = None,
    ):
        self.telemetry = telemetry
        self.factor = float(factor)
        self.min_intervals = int(min_intervals)
        self.poll_interval = float(poll_interval)
        self.clock = clock if clock is not None else telemetry.clock
        self.action = action
        self.action_factor = (
            float(action_factor) if action_factor is not None else None
        )
        self._lock = threading.Lock()
        self._last: float | None = None
        self._beats = 0
        self._intervals: list[float] = []
        self._fired = False
        self._warned = False
        self._acted = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def arm(self) -> None:
        """Start the heartbeat clock (call when the run's first dispatch
        is issued)."""
        with self._lock:
            self._last = self.clock()

    def beat(self) -> None:
        """One chunk landed: record the interval and reset the stall. A
        beat that ends a fired stall episode emits ``stall_recovered``
        and re-arms the one-per-episode warning and action."""
        now = self.clock()
        with self._lock:
            stalled_s = (
                now - self._last
                if self._fired and self._last is not None else None
            )
            if (self._last is not None and self._beats >= 1
                    and stalled_s is None):
                # the interval ending at beat 1 absorbed the first chunk's
                # compile — steady state starts at beat 2. An interval
                # that ends a FIRED stall episode is excluded too: folding
                # the stalled duration into the median silently inflates
                # steady_s, and a second comparable stall then never
                # crosses factor × steady — the re-armed warning and
                # action would go quiet exactly when they matter.
                self._intervals.append(now - self._last)
            self._beats += 1
            beats = self._beats
            self._last = now
            self._fired = False
            self._warned = False
            self._acted = False
        if stalled_s is not None:
            self.telemetry.emit(
                "stall_recovered", stalled_s=stalled_s, chunks_done=beats,
            )
            logger.warning(
                "backend recovered: a chunk landed after a %.1fs stall; "
                "the run continues", stalled_s,
            )

    def steady_s(self) -> float | None:
        """Median steady-state chunk time, or None before enough beats."""
        with self._lock:
            iv = list(self._intervals)
        if len(iv) < self.min_intervals:
            return None
        return sorted(iv)[len(iv) // 2]

    def poll(self) -> bool:
        """Check the heartbeat; emit/warn when stalled, escalate to the
        ``action`` when the stall outlasts ``action_factor`` × steady.
        Returns whether a stall was (newly) flagged."""
        steady = self.steady_s()
        act = None
        with self._lock:
            if self._last is None or steady is None:
                return False
            elapsed = self.clock() - self._last
            if elapsed <= self.factor * steady:
                return False
            newly = not self._fired
            self._fired = True
            warn = not self._warned
            self._warned = True
            beats = self._beats
            if (self.action is not None and self.action_factor is not None
                    and elapsed > self.action_factor * steady
                    and not self._acted):
                self._acted = True
                act = self.action
        if newly:
            self.telemetry.emit(
                "stall_suspected", elapsed_s=elapsed, steady_chunk_s=steady,
                factor=self.factor, chunks_done=beats,
            )
        if warn:
            logger.warning(
                "no chunk completed in %.1fs (> %.0fx the %.2fs "
                "steady-state chunk time) — the backend may be stalled "
                "(dead TPU tunnel?); the run will continue if it recovers",
                elapsed, self.factor, steady,
            )
        if act is not None:
            # the escalation is an anomaly verdict, not just a log line:
            # route it through the pinned detector registry so it emits
            # `anomaly_detected` and can trigger a diagnostic bundle
            from . import detectors

            detectors.fire(
                "stall_escalation", telemetry=self.telemetry,
                elapsed_s=float(elapsed), steady_chunk_s=float(steady),
                action_factor=float(self.action_factor),
                chunks_done=int(beats),
            )
            try:
                act()
            # netrep: allow(exception-taxonomy) — escalation action is best-effort; the watchdog must keep polling for the next stall
            except Exception:  # the action must never kill the watchdog
                logger.warning("stall watchdog action raised", exc_info=True)
        return newly

    # -- thread ------------------------------------------------------------

    def start(self) -> None:
        if self.poll_interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="netrep-stall-watchdog", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll()
            # netrep: allow(exception-taxonomy) — observer thread: a poll bug must degrade to a warning, never kill the monitored run
            except Exception:  # pragma: no cover - must never kill the run
                logger.warning("stall watchdog poll raised", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        self.arm()
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def arm_watchdog(
    telemetry: Telemetry | None,
    action: Callable[[], None] | None = None,
    action_factor: float | None = None,
) -> StallWatchdog | None:
    """Per-null-run watchdog construction shared by the loops: None when
    telemetry is off (the disabled hot path stays a ``None`` check).
    ``action``/``action_factor`` wire the fault runtime's warn→act
    escalation (ISSUE 4) when a fault policy is active."""
    if telemetry is None:
        return None
    wd = StallWatchdog(
        telemetry, factor=telemetry.stall_factor,
        poll_interval=telemetry.watchdog_poll_s,
        action=action, action_factor=action_factor,
    )
    wd.arm()
    wd.start()
    return wd


# ---------------------------------------------------------------------------
# Offline aggregation (the `python -m netrep_tpu telemetry` report)
# ---------------------------------------------------------------------------


def is_event(row: dict) -> bool:
    """Whether a parsed JSON object is a telemetry event line (the check
    ``summarize_watch.py`` shares so mixed logs split cleanly)."""
    return (
        isinstance(row, dict)
        and row.get("v") == SCHEMA_VERSION
        and isinstance(row.get("ev"), str)
        and isinstance(row.get("data"), dict)
    )


def read_events(path: str) -> Iterator[dict]:
    """Stream the event lines of a JSONL file, skipping anything that is
    not a schema-matching event (the sink may share a file with other
    JSONL rows — bench metric lines, watcher headers)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if is_event(row):
                yield row


def aggregate_events(events: Iterable[dict]) -> MetricsRegistry:
    """Fold an event stream into a fresh registry — by construction the
    same numbers the emitting process's live registry held."""
    reg = MetricsRegistry()
    for e in events:
        reg.fold(e["ev"], e["data"], t=e.get("t"), run=e.get("run"))
    return reg


def aggregate_file(path: str) -> MetricsRegistry:
    """Aggregate a telemetry JSONL into a registry (offline CLI report)."""
    return aggregate_events(read_events(path))


def tenant_summary(events: Iterable[dict]) -> dict[str, dict]:
    """Per-tenant aggregation of the serving events (:data:`SERVE_EVENTS`):
    request counts per outcome, latency stats from ``request_done.s``, and
    permutations served — the offline twin of the server's live per-tenant
    counters, derived from the same event stream so the two views cannot
    disagree."""
    out: dict[str, dict] = {}
    for e in events:
        ev = e.get("ev")
        if ev not in SERVE_EVENTS:
            continue
        data = e.get("data", {})
        tenant = data.get("tenant")
        if tenant is None:
            continue
        row = out.setdefault(str(tenant), {
            "received": 0, "packed": 0, "done": 0, "failed": 0,
            "rejected": 0, "expired": 0, "deduped": 0, "perms": 0,
            "latency": [0, 0.0, float("inf"), 0.0],  # n, total, min, max
            "device_s": 0.0, "cost_bytes": 0,
        })
        if ev == "request_cost":
            # attributed cost rollup (ISSUE 13): the offline twin of the
            # server's per-tenant cost counters, folded from the same
            # request_cost events
            if _is_number(data.get("device_s")):
                row["device_s"] += float(data["device_s"])
            if _is_number(data.get("bytes_to_host")):
                row["cost_bytes"] += int(data["bytes_to_host"])
        elif ev == "request_received":
            row["received"] += 1
        elif ev == "request_packed":
            row["packed"] += 1
        elif ev == "request_rejected":
            row["rejected"] += 1
        elif ev == "request_expired":
            row["expired"] += 1
        elif ev == "request_deduped":
            row["deduped"] += 1
        elif ev == "request_done":
            if data.get("ok", True):
                row["done"] += 1
            else:
                row["failed"] += 1
            row["perms"] += int(data.get("perms", 0) or 0)
            s = data.get("s")
            if _is_number(s):
                lat = row["latency"]
                lat[0] += 1
                lat[1] += float(s)
                lat[2] = min(lat[2], float(s))
                lat[3] = max(lat[3], float(s))
    return out


def render_tenants(path: str) -> str:
    """Per-tenant serving section of the CLI report (`python -m netrep_tpu
    telemetry <run.jsonl>`): one row per tenant with outcome counts and
    latency stats. Empty string for logs without serving events."""
    rows = tenant_summary(read_events(path))
    if not rows:
        return ""
    out = ["tenants:"]
    w = max(len(t) for t in rows)
    out.append(
        f"  {'':<{w}}  {'recv':>5} {'done':>5} {'fail':>5} {'rej':>5} "
        f"{'exp':>5} {'dedup':>5} {'perms':>8} {'mean_s':>8} {'max_s':>8} "
        f"{'dev_s':>8}"
    )
    for t in sorted(rows):
        r = rows[t]
        n, tot, _lo, hi = r["latency"]
        mean = tot / n if n else float("nan")
        hi = hi if n else float("nan")
        out.append(
            f"  {t:<{w}}  {r['received']:>5} {r['done']:>5} "
            f"{r['failed']:>5} {r['rejected']:>5} {r['expired']:>5} "
            f"{r['deduped']:>5} {r['perms']:>8} "
            f"{mean:>8.3f} {hi:>8.3f} {r['device_s']:>8.3f}"
        )
    return "\n".join(out)


def format_event(e: dict, t0: float | None = None) -> str:
    """One-line human rendering of an event — the shared renderer of
    ``telemetry --follow`` and the ``top`` dashboard's event tail
    (:mod:`netrep_tpu.serve.top`): relative offset, span markers
    (``>`` opens a span, ``<`` closes one with its duration), event name,
    then the data fields."""
    d = e.get("data") or {}
    off = f"+{e['t'] - t0:9.2f}s" if t0 is not None else f"{e['t']:.2f}"
    mark = " "
    if d.get("span") is not None:
        mark = "<" if _is_number(d.get("s")) else ">"
    parts = " ".join(
        f"{k}={v:g}" if _is_number(v) else f"{k}={v}"
        for k, v in d.items() if k not in ("span", "parent")
    )
    return f"{off} {mark} {e['ev']:<24} {parts}"


def render_recovery(path: str) -> str:
    """Chronological timeline of a run's recovery decisions (the
    ``python -m netrep_tpu telemetry --recovery`` view): every
    :data:`RECOVERY_EVENTS` — and, for fleet logs, :data:`FLEET_EVENTS`
    (a replica loss + failover IS a recovery decision; ``failover_done``
    carries the measured failover time as ``s``) — line with its offset
    from the first event in the file, so "what did the run survive, and
    in what order" reads straight off one screen. Empty string when the
    run never recovered from anything."""
    lines = []
    t0 = None
    for e in read_events(path):
        if t0 is None:
            t0 = e["t"]
        if (e["ev"] not in RECOVERY_EVENTS
                and e["ev"] not in FLEET_EVENTS
                and e["ev"] not in FORENSIC_EVENTS):
            continue
        d = dict(e["data"])
        label = ""
        if e["ev"] in FORENSIC_EVENTS:
            # anomaly verdicts read as first-class timeline entries with
            # their detector name up front (ISSUE 20)
            label = f" [detector={d.pop('detector', '-')}]"
        data = " ".join(f"{k}={v}" for k, v in d.items())
        lines.append(f"+{e['t'] - t0:9.2f}s  {e['ev']:<24}{label} {data}")
    return "\n".join(lines)


def replica_summary(events: Iterable[dict]) -> dict[str, dict]:
    """Per-replica aggregation of the fleet events (:data:`FLEET_EVENTS`)
    — the offline twin of the fleet coordinator's live per-replica rows,
    keyed on the ``replica`` label every fleet event carries: joins,
    losses, shipped records/bytes, failovers (count + total measured
    seconds from ``failover_done.s``), noticed evictions (count + total
    handoff seconds from ``evict_handoff_done.s``), and the replica's
    LAST lifecycle state/generation from the ``replica_state`` stream
    (ISSUE 19)."""
    out: dict[str, dict] = {}
    for e in events:
        ev = e.get("ev")
        if ev not in FLEET_EVENTS:
            continue
        data = e.get("data", {})
        rid = data.get("replica")
        if rid is None:
            continue
        row = out.setdefault(str(rid), {
            "joined": 0, "lost": 0, "shipped_records": 0,
            "shipped_bytes": 0, "failovers": 0, "failover_s": 0.0,
            "evictions": 0, "handoff_s": 0.0, "state": None, "gen": 0,
        })
        if ev == "replica_joined":
            row["joined"] += 1
        elif ev == "replica_lost":
            row["lost"] += 1
        elif ev == "journal_shipped":
            row["shipped_records"] += int(data.get("records", 0) or 0)
            row["shipped_bytes"] += int(data.get("bytes", 0) or 0)
        elif ev == "failover_done":
            row["failovers"] += 1
            if _is_number(data.get("s")):
                row["failover_s"] += float(data["s"])
        elif ev == "evict_notice":
            row["evictions"] += 1
        elif ev == "evict_handoff_done":
            if _is_number(data.get("s")):
                row["handoff_s"] += float(data["s"])
        elif ev == "replica_state":
            row["state"] = data.get("to")
            row["gen"] = int(data.get("gen", 0) or 0)
    return out


def render_replicas(path: str) -> str:
    """Per-replica fleet section of the CLI report (`python -m netrep_tpu
    telemetry <run.jsonl>`), printed beside the per-tenant section for
    logs written by a fleet coordinator. Empty string for logs without
    fleet events."""
    rows = replica_summary(read_events(path))
    if not rows:
        return ""
    out = ["replicas:"]
    w = max(len(r) for r in rows)
    out.append(
        f"  {'':<{w}}  {'state':>8} {'gen':>3} {'join':>5} {'lost':>5} "
        f"{'ship_rec':>9} {'ship_B':>9} {'failover':>9} {'fo_s':>8} "
        f"{'evict':>5} {'ho_s':>8}"
    )
    for rid in sorted(rows):
        r = rows[rid]
        out.append(
            f"  {rid:<{w}}  {(r['state'] or '-'):>8} {r['gen']:>3} "
            f"{r['joined']:>5} {r['lost']:>5} "
            f"{r['shipped_records']:>9} {r['shipped_bytes']:>9} "
            f"{r['failovers']:>9} {r['failover_s']:>8.3f} "
            f"{r['evictions']:>5} {r['handoff_s']:>8.3f}"
        )
    return "\n".join(out)


def grid_summary(events: Iterable[dict]) -> dict:
    """Aggregation of the all-pairs grid events (:data:`GRID_EVENTS`) —
    one row per DISCOVERY dataset (a grid row shares its discovery-side
    work, so that is the axis along which dedup and warm starts pay off)
    plus grid-level totals: dedup hits, grid count, and summed grid wall
    time from the ``grid_end`` span duration. Returns
    ``{"rows": {discovery: {...}}, "grids", "dedup_hits", "wall_s"}``;
    ``rows`` is empty when the log has no grid events."""
    rows: dict[str, dict] = {}
    out = {"rows": rows, "grids": 0, "dedup_hits": 0, "wall_s": 0.0}
    for e in events:
        ev = e.get("ev")
        if ev not in GRID_EVENTS:
            continue
        data = e.get("data", {})
        if ev == "grid_dedup_hit":
            out["dedup_hits"] += 1
            continue
        if ev == "grid_end":
            out["grids"] += 1
            if _is_number(data.get("s")):
                out["wall_s"] += float(data["s"])
            continue
        d = data.get("discovery")
        if d is None:
            continue
        row = rows.setdefault(str(d), {
            "started": 0, "computed": 0, "manifest": 0,
            "warmstarted": 0, "perms": 0, "prior_perms": 0,
        })
        if ev == "grid_cell_start":
            row["started"] += 1
        elif ev == "grid_cell_done":
            src = data.get("source")
            if src == "manifest":
                row["manifest"] += 1
            else:
                row["computed"] += 1
            row["perms"] += int(data.get("perms", 0) or 0)
        elif ev == "grid_warmstart_seeded":
            row["warmstarted"] += 1
            row["prior_perms"] += int(data.get("prior_perms", 0) or 0)
    return out


def render_grid(path: str) -> str:
    """All-pairs grid section of the CLI report (`python -m netrep_tpu
    telemetry <run.jsonl>`): per-discovery-row cell outcomes (computed vs
    answered from the manifest, warm starts, permutations evaluated) and
    a totals line with the dedup hit count and grid wall time. Empty
    string for logs without grid events."""
    s = grid_summary(read_events(path))
    if not s["rows"] and not s["grids"]:
        return ""
    out = ["grid:"]
    out.append(
        f"  grids={s['grids']} dedup_hits={s['dedup_hits']} "
        f"wall_s={s['wall_s']:.3f}"
    )
    if s["rows"]:
        w = max(len(d) for d in s["rows"])
        out.append(
            f"  {'':<{w}}  {'cells':>5} {'comp':>5} {'manif':>5} "
            f"{'warm':>5} {'perms':>9} {'prior':>9}"
        )
        for d in sorted(s["rows"]):
            r = s["rows"][d]
            out.append(
                f"  {d:<{w}}  {r['started']:>5} {r['computed']:>5} "
                f"{r['manifest']:>5} {r['warmstarted']:>5} "
                f"{r['perms']:>9} {r['prior_perms']:>9}"
            )
    return "\n".join(out)
