"""Textual progress for the permutation null — the rebuild of the
reference's C++-main-thread progress bar (SURVEY.md §5 "Metrics / logging":
``verbose=TRUE`` prints stage messages and a textual progress bar). The
engine's chunked host loop already reports ``(done, total)`` per chunk; this
renders it: a carriage-return bar on TTYs, throttled ~2 updates/s, and
plain log-style lines every ~10% on non-interactive streams so CI logs
stay readable.
"""

from __future__ import annotations

import sys
import time
from typing import Callable


def resolve_progress(
    progress: Callable[[int, int], None] | None, verbose: bool
) -> Callable[[int, int], None] | None:
    """The one rule for both API surfaces (dense and sparse): a user
    callback wins; otherwise ``verbose=True`` gets the default printer."""
    if progress is not None:
        return progress
    return make_progress_printer() if verbose else None


def make_progress_printer(
    stream=None,
    min_interval: float = 0.5,
    bar_width: int = 28,
    _clock: Callable[[], float] = time.monotonic,
) -> Callable[[int, int], None]:
    """Build a ``(done, total)`` callback rendering permutation progress.

    One printer per (discovery, test) pair. Rate and ETA are measured from
    the first callback onward — ``(done - done0) / elapsed`` — so the
    first chunk's compile time and any checkpoint-resumed permutations from
    a previous session don't inflate the rate; the very first line shows no
    rate (nothing has been measured yet).
    """
    if stream is None:
        stream = sys.stderr
    tty = bool(getattr(stream, "isatty", lambda: False)())
    state = {"t0": None, "done0": 0, "last": float("-inf"), "last_frac": -1.0}

    def cb(done: int, total: int) -> None:
        now = _clock()
        first = state["t0"] is None
        if first:
            state["t0"], state["done0"] = now, done
        finished = done >= total
        if tty:
            if not finished and not first and now - state["last"] < min_interval:
                return
        else:
            # non-interactive: a line per ~10% step (and the final line)
            frac_step = int(10 * done / total) if total else 10
            if not finished and frac_step <= state["last_frac"]:
                return
            state["last_frac"] = frac_step
        state["last"] = now
        elapsed = now - state["t0"]
        measured = done - state["done0"]
        rate = measured / elapsed if elapsed > 0 and measured > 0 else None
        eta = (total - done) / rate if rate else float("inf")
        frac = done / total if total else 1.0
        rate_s = f"{rate:8.1f}/s" if rate else " " * 8 + "-/s"
        if tty:
            filled = int(bar_width * frac)
            bar = "=" * filled + " " * (bar_width - filled)
            end = "\n" if finished else ""
            stream.write(
                f"\r[{bar}] {done}/{total} perms "
                f"({100 * frac:5.1f}%) {rate_s} ETA {eta:6.1f}s{end}"
            )
        else:
            stream.write(
                f"permutations: {done}/{total} ({100 * frac:.0f}%), "
                f"{rate_s.strip()}, ETA {eta:.0f}s\n"
            )
        stream.flush()

    return cb
