"""Analytic FLOP/HBM-byte cost model + roofline gauges (ISSUE 18).

Every perf claim the repo makes — fused kernel, ring DMA, tile
screening, bf16 rescue, AOT warm start — was judged only by wall-clock
and the hand-written roofline *predictions* in BASELINE.md. This module
is the measurement side: an analytic per-permutation FLOP/byte model per
program family, a per-device-kind peak-rate table, and the run-time
tracker the null loops thread through every chunk/superchunk span so
"what fraction of speed of light did this run achieve" is a recorded
number, not prose.

Model contract (docs/architecture.md § Roofline observability):

- costs are **integers per permutation** derived from the engine's
  bucket signature (cap, module count), matrix width ``n``, sample count
  ``s``, power-iteration count ``p``, and dtype width — the SAME integer
  feeds the chunk event, the :class:`~netrep_tpu.utils.profiling.NullProfile`
  accumulator, and the ``null_run_end`` totals, so per-family span sums
  reconcile with profile totals *exactly* (no float re-derivation);
- the model is cross-checkable against ``Compiled.cost_analysis()``
  where the installed jax exposes it (:func:`xla_cost_analysis`, guarded
  like the PR 5 xplane probes). XLA counts ``lax.scan``/``while`` bodies
  ONCE regardless of trip count (verified on the installed jax), so
  :attr:`ProgramCost.xla_flops_per_perm` prices scan-carried terms (the
  power iteration) at one trip for that comparison while
  :attr:`ProgramCost.flops_per_perm` prices the work actually executed;
- peak rates come from :data:`PEAK_TABLE` keyed by ``device_kind`` (the
  public per-chip dense-matmul and HBM-bandwidth specs), overridable via
  the ``NETREP_PEAK_OVERRIDES`` env JSON; an unknown kind (CPU included)
  reports utilisation as **null, never a guess** — the bench/watch
  summarizers classify those rows as mechanism checks, not measurements.

Telemetry-off runs never reach this module (the engine resolves the
tracker inside its single telemetry ``None`` check — the PR 3 contract).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading

logger = logging.getLogger("netrep_tpu")

#: roofline-block schema version: the ``roofline`` telemetry event, the
#: optional perf-ledger ``roofline`` block, and bench rows all carry
#: blocks of this shape (bump deliberately, with the pinned tests)
ROOFLINE_VERSION = 1

#: env var holding a JSON object of per-device-kind peak-rate overrides:
#: ``{"<device_kind>": [flops_per_s, hbm_bytes_per_s]}`` (a two-element
#: array, or an object with ``"flops"``/``"bw"`` keys). Lets a deployment
#: calibrate the table to its chips — and lets CPU CI give the ``cpu``
#: kind a peak so utilisation gauges are exercised in tier-1.
PEAK_OVERRIDES_ENV = "NETREP_PEAK_OVERRIDES"

#: per-device-kind peak rates ``(dense flops/s, HBM bytes/s)`` per chip —
#: the public spec numbers (dense bf16 matmul peak; XLA's default-precision
#: f32 matmul runs on the same MXU passes, so this is the honest ceiling
#: for the gather/stat matmuls). Keys are normalized lowercase
#: ``device_kind`` strings. CPU and unknown kinds are deliberately absent:
#: utilisation is then null, never a guess (override via env to opt in).
PEAK_TABLE: dict[str, tuple[float, float]] = {
    "tpu v2": (45e12, 700e9),
    "tpu v3": (123e12, 900e9),
    "tpu v4": (275e12, 1228e9),
    "tpu v5 lite": (197e12, 819e9),
    "tpu v5e": (197e12, 819e9),
    "tpu v5": (459e12, 2765e9),
    "tpu v5p": (459e12, 2765e9),
    "tpu v6 lite": (918e12, 1640e9),
    "tpu v6e": (918e12, 1640e9),
}

_OVERRIDES_WARNED = False


def device_kind() -> str:
    """``device_kind`` of the default backend's first device, or
    ``"unknown"`` when no backend resolves — the peak-table key."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    # netrep: allow(exception-taxonomy) — backend probe: no resolvable device just disables utilisation gauges
    except Exception:
        return "unknown"


def _peak_overrides() -> dict[str, tuple[float, float]]:
    global _OVERRIDES_WARNED
    raw = os.environ.get(PEAK_OVERRIDES_ENV)
    if not raw:
        return {}
    try:
        doc = json.loads(raw)
        if not isinstance(doc, dict):
            raise ValueError("not a JSON object")
        out = {}
        for kind, v in doc.items():
            if isinstance(v, dict):
                pair = (float(v["flops"]), float(v["bw"]))
            else:
                pair = (float(v[0]), float(v[1]))
            out[str(kind).strip().lower()] = pair
        return out
    except (ValueError, TypeError, KeyError, IndexError) as e:
        if not _OVERRIDES_WARNED:
            _OVERRIDES_WARNED = True
            logger.warning(
                "%s is not a valid peak-override JSON object (%s: %s); "
                "ignoring it", PEAK_OVERRIDES_ENV, type(e).__name__, e,
            )
        return {}


def device_peaks(kind: str | None = None) -> tuple[float, float] | None:
    """``(peak_flops_per_s, peak_hbm_bytes_per_s)`` for a device kind
    (default: the current backend's), or None when the kind is unknown —
    callers then report utilisation as null. Env overrides win over the
    built-in table."""
    k = (kind if kind is not None else device_kind()).strip().lower()
    over = _peak_overrides()
    if k in over:
        return over[k]
    return PEAK_TABLE.get(k)


# ---------------------------------------------------------------------------
# the analytic per-permutation model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """Per-permutation cost of one engine's null-chunk program family.

    ``flops_per_perm`` prices the work executed (power iteration × its
    trip count); ``xla_flops_per_perm`` prices scan-carried terms at ONE
    trip — the number comparable against ``Compiled.cost_analysis()``,
    which counts loop bodies once on the installed jax. Both are integers
    so downstream sums reconcile exactly.
    """

    family: str
    flops_per_perm: int
    bytes_per_perm: int
    xla_flops_per_perm: int
    n_tests: int = 1


def _stats_flops(m: int, s: int | None, p: int, summary: str,
                 topo: bool = True) -> tuple[int, int]:
    """Seven-statistic body flops per (module × permutation) at bucket
    cap ``m``: returns ``(executed, xla_equivalent)``. Topology terms
    (avg weight, degree, corr-of-corr) sum/correlate over the m×m
    submatrices; data terms standardize the (s, m) slice, build the
    node-space Gram, power-iterate it ``p`` times (the scan XLA counts
    once), and correlate node contributions (ops/stats.py)."""
    f = fx = 0
    if topo:
        f += 16 * m * m + 10 * m
    if s:
        f += 6 * m * s            # standardize_masked
        f += 2 * m * m * s        # gram Z^T Z
        f += 10 * m * s           # profile + node-contribution einsums/norms
        if topo:
            f += 2 * m * m        # avg_cor sign-weighted sum
        f += 40 * m               # masked pearsons / means over nodes
        it = 12 * m ** 3 if summary == "eigh" else 2 * m * m + 5 * m
        fx = f + it               # scan body priced once
        f += it if summary == "eigh" else p * it
    else:
        fx = f
    return f, fx


def _module_cost(family: str, m: int, n: int | None, s: int | None,
                 p: int, n_mats: int, derived: bool, itemsize: int,
                 summary: str) -> tuple[int, int, int]:
    """(flops, bytes, xla_flops) per (module × permutation) at cap ``m``.

    Gather pricing per family (docs/architecture.md for the derivation):

    - ``mxu``: sorted row gather (m·n bytes/matrix) + one-hot column
      matmul (2·m²·n) + unsort rotation PᵀSP (4·m³); data slice adds an
      m·s row gather + 2·m²·s unsort matmul;
    - ``direct``: exact 2D advanced-index gather — m² bytes/matrix,
      negligible flops;
    - ``fused`` (pallas gather and/or mega-kernel): streams whole m·n row
      blocks tile-by-tile with mask-select compares (~2·m·n);
    - ``data-only``: no stored n×n matrices at all — the m·s data slice,
      the test-side k×k correlation reusing the node-space Gram the data
      statistics already price, plus the soft-threshold network
      construction (2·m²), then the full seven statistics;
    - derived networks (``net_beta``) drop one stored matrix from the
      row traffic and add an elementwise |corr|**β (2·m²).
    """
    topo = n is not None
    gf = by = 0
    if topo and family != "data-only":
        if family.startswith("mxu"):
            gf += n_mats * (2 * m * m * n + 4 * m ** 3)
            if s:
                gf += 2 * m * m * s
            by += n_mats * m * n * itemsize
        elif family.startswith("fused"):
            gf += n_mats * 2 * m * n
            by += n_mats * m * n * itemsize
        else:                      # direct 2D gather
            by += n_mats * m * m * itemsize
        if derived:
            gf += 2 * m * m
    if family == "data-only" and s:
        gf += 2 * m * m
    if s:
        by += m * s * itemsize
    sf, sfx = _stats_flops(m, s, p, summary, topo=topo)
    return gf + sf, by, gf + sfx


def _first(x):
    return x[0] if isinstance(x, (list, tuple)) else x


def _test_shapes(engine) -> tuple[int | None, int | None]:
    """(n nodes, s samples) of the test side — single-test attrs first,
    then the multi-test stacked/ragged layouts (first dataset's shape;
    sample counts are uniform across cohorts on the hot paths)."""
    n = s = None
    tc = getattr(engine, "_test_corr", None)
    if tc is None:
        tc = _first(getattr(engine, "_tc", None))
        if tc is not None:
            n = int(tc.shape[-1])
    else:
        n = int(tc.shape[-1])
    td = getattr(engine, "_test_dataT", None)
    if td is None:
        td = _first(getattr(engine, "_td", None))
    if td is not None:
        s = int(td.shape[-1])
        if n is None:
            n = int(td.shape[-2])
    return n, s


def _dtype_itemsize(config) -> int:
    dt = getattr(config, "dtype", "float32")
    try:
        import numpy as np

        return int(np.dtype(dt).itemsize)
    except TypeError:
        try:
            import jax.numpy as jnp

            return int(jnp.dtype(dt).itemsize)
        except (ImportError, TypeError):
            return 4


def resolve_engine_cost(engine) -> ProgramCost | None:
    """Analytic per-permutation cost of ``engine``'s null-chunk program,
    or None for engines without the JAX bucket structure (the native C++
    tier) — cost fields are then simply omitted, never guessed. Every
    attribute access is getattr-guarded: a cost model that cannot resolve
    must not fail the run that asked for it."""
    base = getattr(engine, "_base", None) or engine
    buckets = getattr(engine, "buckets", None)
    if not buckets:
        buckets = getattr(base, "buckets", None)
    config = getattr(engine, "config", None)
    if config is None:
        config = getattr(base, "config", None)
    if not buckets or config is None:
        return None
    data_only = bool(getattr(base, "data_only", False)
                     or getattr(engine, "data_only", False))
    gather_mode = str(getattr(engine, "gather_mode", None)
                      or getattr(base, "gather_mode", "direct"))
    stat_mode = str(getattr(engine, "stat_mode", None)
                    or getattr(base, "stat_mode", "xla"))
    net_beta = getattr(engine, "net_beta", None)
    n, s = _test_shapes(engine)
    if n is None:
        return None
    if data_only:
        family = "data-only"
    elif stat_mode == "fused":
        family = f"{gather_mode}+fusedstats"
    else:
        family = gather_mode
    itemsize = _dtype_itemsize(config)
    if getattr(engine, "_screen_active", False):
        # bf16 screened fast pass (ISSUE 16): the chunk dispatch wraps
        # the bf16 pass + the exact rescue of flagged permutations; the
        # model prices the pass every permutation pays (bf16-width row
        # traffic) — rescue cost is excluded, documented, since the
        # rescued fraction is data-dependent and telemetry already
        # counts rescue_dispatch events separately.
        family += "+bf16rescue"
        itemsize = 2
    T = int(getattr(engine, "T", 1) or 1)
    p = int(getattr(config, "power_iters", 60) or 60)
    summary = str(getattr(config, "summary_method", "power") or "power")
    n_mats = 1 if net_beta is not None else 2
    f = by = fx = 0
    for bkt in buckets:
        k = len(getattr(bkt, "module_pos", ()) or ())
        m = int(getattr(bkt, "cap", 0) or 0)
        if not k or not m:
            continue
        mf, mb, mfx = _module_cost(family, m, n, s, p, n_mats,
                                   net_beta is not None, itemsize, summary)
        f += k * mf
        by += k * mb
        fx += k * mfx
    if not f and not by:
        return None
    return ProgramCost(family, int(f) * T, int(by) * T, int(fx) * T, T)


# ---------------------------------------------------------------------------
# roofline math
# ---------------------------------------------------------------------------


def sol_pps(flops_per_perm: int, bytes_per_perm: int,
            peaks: tuple[float, float] | None) -> float | None:
    """Speed-of-light permutations/s: 1 / max(compute time, HBM time)
    per permutation — the roofline ceiling. None when peaks are unknown."""
    if peaks is None:
        return None
    pf, pb = peaks
    if pf <= 0 or pb <= 0:
        return None
    sol_s = max(flops_per_perm / pf, bytes_per_perm / pb)
    return (1.0 / sol_s) if sol_s > 0 else None


def utilisation(achieved_pps: float | None,
                sol: float | None) -> float | None:
    """Achieved fraction of speed of light (null when either side is
    unknown — never a guess)."""
    if achieved_pps is None or sol is None or sol <= 0:
        return None
    return achieved_pps / sol


class RunCostTracker:
    """Per-run cost accumulator the null loops thread through their
    telemetry branch: prices each chunk/superchunk with the SAME integers
    it feeds the :class:`~netrep_tpu.utils.profiling.NullProfile`, so
    span sums and profile totals reconcile exactly. Resolved only when
    telemetry is on (the PR 3 single-None-check contract); adaptive loops
    call :meth:`refresh` after a rebucket so shrunken bucket lists are
    re-priced mid-run."""

    def __init__(self, cost: ProgramCost, kind: str | None = None):
        self.cost = cost
        self.device_kind = kind if kind is not None else device_kind()
        self.peaks = device_peaks(self.device_kind)
        self.flops = 0
        self.bytes_hbm = 0
        self.perms = 0

    def refresh(self, engine) -> None:
        cost = resolve_engine_cost(engine)
        if cost is not None:
            self.cost = cost

    def chunk_fields(self, take: int, seconds: float,
                     profile=None) -> dict:
        """Accumulate one chunk/superchunk and return its event fields
        (``family``/``flops``/``bytes_hbm``/``achieved_pps``/
        ``utilisation``)."""
        f = self.cost.flops_per_perm * int(take)
        b = self.cost.bytes_per_perm * int(take)
        self.flops += f
        self.bytes_hbm += b
        self.perms += int(take)
        if profile is not None:
            profile.record_cost(f, b, self.cost.family, int(take))
        pps = (take / seconds) if seconds > 0 else None
        sol = sol_pps(self.cost.flops_per_perm, self.cost.bytes_per_perm,
                      self.peaks)
        return {
            "family": self.cost.family,
            "flops": int(f),
            "bytes_hbm": int(b),
            "achieved_pps": pps,
            "utilisation": utilisation(pps, sol),
        }

    def run_fields(self, elapsed_s: float) -> dict:
        """``null_run_end`` extras: accumulated totals + whole-run rate."""
        pps = (self.perms / elapsed_s) if elapsed_s > 0 else None
        sol = sol_pps(self.cost.flops_per_perm, self.cost.bytes_per_perm,
                      self.peaks)
        return {
            "family": self.cost.family,
            "flops": int(self.flops),
            "bytes_hbm": int(self.bytes_hbm),
            "achieved_pps": pps,
            "utilisation": utilisation(pps, sol),
        }

    def roofline_block(self, achieved_pps: float | None) -> dict:
        """The additive ledger/bench/event block (``ROOFLINE_VERSION``
        shape): the per-perm model, the peak table row it was judged
        against, and the achieved-vs-speed-of-light verdict."""
        pf, pb = self.peaks if self.peaks is not None else (None, None)
        sol = sol_pps(self.cost.flops_per_perm, self.cost.bytes_per_perm,
                      self.peaks)
        util = utilisation(achieved_pps, sol)
        rnd = lambda v: round(float(v), 4) if v is not None else None
        return {
            "family": self.cost.family,
            "flops_per_perm": int(self.cost.flops_per_perm),
            "bytes_per_perm": int(self.cost.bytes_per_perm),
            "flops": int(self.flops),
            "bytes_hbm": int(self.bytes_hbm),
            "device_kind": self.device_kind,
            "peak_flops": pf,
            "peak_bw": pb,
            "sol_pps": rnd(sol),
            "achieved_pps": rnd(achieved_pps),
            "utilisation": rnd(util),
        }


def tracker_for(engine) -> RunCostTracker | None:
    """The engine-loop entry point: a tracker when the analytic model
    resolves, else None (native engines — cost fields omitted)."""
    cost = resolve_engine_cost(engine)
    return RunCostTracker(cost) if cost is not None else None


# ---------------------------------------------------------------------------
# last-run note: the in-process seam bench rows and fleet stats() read
# ---------------------------------------------------------------------------

_NOTE_LOCK = threading.Lock()
_LAST_RUN_NOTE: dict | None = None


def record_run_note(note: dict) -> None:
    """Record the most recent telemetry-on run's roofline block —
    written by the engine's end-of-run accounting, read by bench rows
    (consume semantics, so a stale note never leaks onto an unrelated
    row) and by the serve scheduler's ``stats()`` (peek semantics)."""
    global _LAST_RUN_NOTE
    with _NOTE_LOCK:
        _LAST_RUN_NOTE = dict(note)


def last_run_note(consume: bool = False) -> dict | None:
    global _LAST_RUN_NOTE
    with _NOTE_LOCK:
        note = _LAST_RUN_NOTE
        if consume:
            _LAST_RUN_NOTE = None
        return dict(note) if note is not None else None


# ---------------------------------------------------------------------------
# guarded XLA cross-check probes (the PR 5 xplane-probe pattern)
# ---------------------------------------------------------------------------

_COST_ANALYSIS_WARNED = False


def xla_cost_analysis(compiled) -> dict | None:
    """``Compiled.cost_analysis()`` where the installed jax exposes it,
    normalized to ``{"flops", "bytes_accessed"}`` floats. The return
    shape shifts across releases (a list of dicts on the installed
    version, a bare dict on others); any incompatibility degrades to None
    with one warning — the analytic model stands alone, the XLA number is
    a cross-check."""
    global _COST_ANALYSIS_WARNED
    fn = getattr(compiled, "cost_analysis", None)
    if not callable(fn):
        return None
    try:
        ca = fn()
    # netrep: allow(exception-taxonomy) — optional-API probe: an incompatible jax only disables the cross-check
    except Exception as e:
        if not _COST_ANALYSIS_WARNED:
            _COST_ANALYSIS_WARNED = True
            logger.warning("cost_analysis() unavailable on this jax "
                           "(%s: %s); analytic model is not cross-checked",
                           type(e).__name__, e)
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
        v = ca.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[dst] = float(v)
    return out or None


def xla_memory_analysis(compiled) -> dict | None:
    """``Compiled.memory_analysis()`` normalized to plain ints (argument/
    output/temp/code sizes), or None where unsupported — same guard
    policy as :func:`xla_cost_analysis`."""
    fn = getattr(compiled, "memory_analysis", None)
    if not callable(fn):
        return None
    try:
        ma = fn()
    # netrep: allow(exception-taxonomy) — optional-API probe: an incompatible jax only disables the cross-check
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "generated_code_size_in_bytes"):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[attr] = int(v)
    return out or None


# ---------------------------------------------------------------------------
# run-JSONL folding (the `roofline` CLI's table source)
# ---------------------------------------------------------------------------


def fold_roofline_events(events) -> dict:
    """Fold a telemetry run's events into the per-family roofline view:

    - ``families``: per-family accumulators summed over every chunk/
      superchunk span carrying cost fields (perms, flops, bytes_hbm,
      wall seconds, span count);
    - ``run_totals``: the ``null_run_end`` totals per family (the
      reconciliation counterpart — span sums must equal these exactly);
    - ``runs``: each ``roofline`` event's block (per-perm model, peaks,
      utilisation verdict).
    """
    fams: dict[str, dict] = {}
    run_totals: dict[str, dict] = {}
    runs: list[dict] = []
    for e in events:
        if not isinstance(e, dict):
            continue
        ev = e.get("ev")
        d = e.get("data") or {}
        if ev in ("chunk", "superchunk") and isinstance(d.get("family"), str):
            fl = d.get("flops")
            if not isinstance(fl, (int, float)) or isinstance(fl, bool):
                continue
            a = fams.setdefault(d["family"], {
                "perms": 0, "flops": 0, "bytes_hbm": 0, "s": 0.0,
                "spans": 0, "utilisation": None,
            })
            a["perms"] += int(d.get("take") or d.get("perms") or 0)
            a["flops"] += int(fl)
            a["bytes_hbm"] += int(d.get("bytes_hbm") or 0)
            a["s"] += float(d.get("s") or 0.0)
            a["spans"] += 1
            if isinstance(d.get("utilisation"), (int, float)):
                a["utilisation"] = float(d["utilisation"])
        elif ev == "null_run_end" and isinstance(d.get("family"), str):
            t = run_totals.setdefault(d["family"],
                                      {"flops": 0, "bytes_hbm": 0})
            t["flops"] += int(d.get("flops") or 0)
            t["bytes_hbm"] += int(d.get("bytes_hbm") or 0)
        elif ev == "roofline":
            runs.append(dict(d))
    return {"families": fams, "run_totals": run_totals, "runs": runs}


def _fmt(v, spec: str = ".3g") -> str:
    if v is None:
        return "-"
    return format(float(v), spec)


def render_roofline(folded: dict) -> str:
    """The ``roofline`` CLI's per-family headroom table, sorted by
    headroom (1 − utilisation) descending — the biggest optimization
    targets first; families whose device has no peak entry render
    utilisation/headroom as ``-`` and sort as full headroom. Ends with
    the reconciliation verdict: per-family span sums vs the
    ``null_run_end`` totals, which the model contract says must match
    *exactly*."""
    fams = folded.get("families") or {}
    totals = folded.get("run_totals") or {}
    runs = folded.get("runs") or []
    if not fams and not runs:
        return "roofline: no cost-carrying chunk/superchunk events"
    latest: dict[str, dict] = {}
    for r in runs:
        if isinstance(r.get("family"), str):
            latest[r["family"]] = r
    kinds = {str(r.get("device_kind")) for r in runs
             if r.get("device_kind") is not None}
    rows = []
    for fam, a in fams.items():
        ach = (a["perms"] / a["s"]) if a.get("s") else None
        r = latest.get(fam, {})
        sol = r.get("sol_pps")
        util = (utilisation(ach, float(sol))
                if isinstance(sol, (int, float)) else None)
        head = (1.0 - util) if util is not None else None
        rows.append((fam, a, ach, sol, util, head))
    rows.sort(key=lambda x: (-(x[5] if x[5] is not None else 1.0), x[0]))
    lines = [
        f"roofline: {len(rows)} famil{'y' if len(rows) == 1 else 'ies'}, "
        f"device kind {'/'.join(sorted(kinds)) or 'unknown'}",
        f"  {'family':<22} {'spans':>5} {'perms':>9} {'flops':>9} "
        f"{'bytes':>9} {'pps':>9} {'sol_pps':>9} {'util':>6} {'head':>6}",
    ]
    for fam, a, ach, sol, util, head in rows:
        lines.append(
            f"  {fam:<22} {a.get('spans', 0):>5} {a.get('perms', 0):>9} "
            f"{_fmt(a.get('flops')):>9} {_fmt(a.get('bytes_hbm')):>9} "
            f"{_fmt(ach):>9} {_fmt(sol):>9} "
            f"{_fmt(util, '.2f'):>6} {_fmt(head, '.2f'):>6}"
        )
    if totals:
        bad = [
            fam for fam, t in totals.items()
            if (fams.get(fam, {}).get("flops") != t.get("flops")
                or fams.get(fam, {}).get("bytes_hbm") != t.get("bytes_hbm"))
        ]
        if bad:
            lines.append(
                "  RECONCILIATION MISMATCH: span sums != null_run_end "
                f"totals for {', '.join(sorted(bad))}"
            )
        else:
            lines.append(
                f"  reconciled: span sums == null_run_end totals for "
                f"{len(totals)} famil{'y' if len(totals) == 1 else 'ies'}"
            )
    return "\n".join(lines)
