"""Persistent serialized-executable store — zero-compile warm start (ISSUE 15).

The warm :class:`~netrep_tpu.serve.pool.ProgramPool` (ISSUE 7) amortizes
the jit-compile tax *within* a process; nothing amortizes it *across*
processes — every replica boot, CLI run, and ``chaos --fleet`` respawn
re-traces and re-compiles the bucketed null programs from scratch, the
seconds-scale cost the PR 14 ``serve-fleet-coldstart`` ledger entries
measure. This module closes that gap with a fingerprinted store of
``jax.export``-serialized programs:

- **Export** (``netrep warmup``, or any run under ``NETREP_AOT_EXPORT=1``):
  each program (chunk body, superchunk scan, fused/adaptive counter,
  observed pass, grouped-keys helpers) is traced once, lowered to
  portable StableHLO, serialized to the store, and compiled once so the
  XLA executable lands in the persistent compile cache beside it.
- **Load** (any later process): the program deserializes — skipping
  tracing and jax-level lowering entirely — and its XLA compile hits the
  persistent cache, so the first request runs at steady-state speed:
  ``compile_span → ~0`` with ``source: aot``.
- **Fallback ladder** (never wrong, only slower): entry absent, written
  by a different jax/jaxlib/device/PRNG environment, corrupt, or failing
  to deserialize/compile ⇒ the normal ``jax.jit`` path compiles exactly
  as before. Corrupt entries are quarantined (renamed ``*.bad``), never
  fatal; environment mismatches invalidate silently with a one-shot
  warning and an ``aot_store_miss`` telemetry event.

**Bit-identity contract**: an AOT-loaded program is the SAME StableHLO
the jit path lowers (the store serializes the traced program, it never
re-derives it), so counts, p-values, and adaptive decisions are pinned
bit-identical to the jit path in all four null-loop modes
(tests/test_aot.py). Typed PRNG key arrays cross the export boundary as
their raw ``uint32`` key data (jax 0.4's export cannot serialize extended
dtypes in the calling convention); ``wrap_key_data``/``key_data`` are
bit-exact inverses, so the bridge cannot perturb a single draw.

**Identity discipline**: entries are keyed by the engine's
``autotune_key()`` fingerprint × the program's closed-over constants ×
the abstract argument signature, and validated against jax/jaxlib
version, backend platform, device kind, and default PRNG impl recorded
in each entry's meta sidecar — an engine differing in ANY fingerprint
component never shares an entry (tests pin this per component). The
store lives beside the persistent XLA compile cache
(``.jax_cache/<cpu-fingerprint>/aot/``) under the same host isolation
rule, and a size-bounded LRU GC (``NETREP_AOT_STORE_MAX_MB``) keeps it
from growing without bound.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import threading
import time

logger = logging.getLogger("netrep_tpu")

#: store directory override (default: ``.jax_cache/<cpu-fp>/aot`` beside
#: the persistent XLA compile cache)
STORE_ENV = "NETREP_AOT_STORE"
#: ``1`` ⇒ runs export programs they had to jit-compile (the warmup CLI
#: sets this implicitly via :meth:`ProgramStore.exporting`)
EXPORT_ENV = "NETREP_AOT_EXPORT"
#: ``0`` ⇒ the store is disabled entirely: every acquisition jits
DISABLE_ENV = "NETREP_AOT"
#: LRU GC bound for the on-disk store, in MiB (default 512)
MAX_MB_ENV = "NETREP_AOT_STORE_MAX_MB"

#: meta-sidecar format (bump deliberately, with the store tests)
META_FORMAT = 1

#: in-process memo bound: compiled program dispatchers kept alive across
#: engine instances (the cross-engine analogue of the warm engine pool)
_MEMO_MAX = 64

_WARNED: set[str] = set()


def _telemetry():
    from .telemetry import current

    return current()


def _emit(ev: str, **data) -> None:
    tel = _telemetry()
    if tel is not None:
        tel.emit(ev, **data)


def _warn_once(reason: str, msg: str, *args) -> None:
    """One-shot warning per reason class — store hygiene must be audible
    exactly once, never a per-chunk log storm."""
    if reason not in _WARNED:
        _WARNED.add(reason)
        logger.warning(msg, *args)


def default_dir() -> str:
    """Store beside the persistent XLA compile cache:
    ``.jax_cache/<cpu-fingerprint>/aot`` (the same host-isolation rule —
    see :func:`netrep_tpu.utils.backend.host_cpu_fingerprint`)."""
    from .backend import host_cpu_fingerprint

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(
        repo_root, ".jax_cache", host_cpu_fingerprint(), "aot"
    )


_CODE_SIG: str | None = None


def code_signature() -> str:
    """Content digest of the package's own source files, computed once
    per process. jax's persistent compile cache is content-addressed (it
    keys on the HLO itself) and cannot serve a stale program; THIS store
    keys on metadata, so without a code component an edit to a program
    body whose fingerprint/constants happen not to change would silently
    serve the pre-edit program. Any package edit therefore invalidates
    every entry — conservative, and the store re-warms itself via
    ``warmup`` / export-on-miss."""
    global _CODE_SIG
    if _CODE_SIG is None:
        h = hashlib.sha256()
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))
        for dirpath, dirnames, filenames in sorted(os.walk(pkg_root)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                h.update(os.path.relpath(p, pkg_root).encode())
                try:
                    with open(p, "rb") as f:
                        h.update(f.read())
                except OSError:
                    pass
        _CODE_SIG = h.hexdigest()[:16]
    return _CODE_SIG


def env_signature() -> str:
    """The environment identity an entry is only ever valid within:
    jax × jaxlib version, backend platform, device kind, the default
    PRNG impl (the raw-key bridge re-wraps key data under it), and the
    package source digest (:func:`code_signature`). Any mismatch
    invalidates the entry — serialized StableHLO is portable in
    principle, but cross-version/device/code reuse is exactly the
    silent-wrong-speed risk this store refuses to take."""
    import jax
    import jaxlib

    try:
        dev = jax.devices()[0]
        kind = f"{dev.platform}:{getattr(dev, 'device_kind', '?')}"
    except RuntimeError:
        kind = "none"
    impl = str(jax.config.jax_default_prng_impl)
    return (f"jax:{jax.__version__}|jaxlib:{jaxlib.__version__}"
            f"|dev:{kind}|prng:{impl}|code:{code_signature()}")


def program_key(autotune_key: str, constants: str, mesh_spec: str) -> str:
    """Logical identity of one engine program: the engine's autotune/
    compile-cache fingerprint (backend × gather/stat mode × bucket caps ×
    chunk × program name), the program's closed-over constants (the parts
    the abstract argument signature cannot see — slices, net_beta,
    summary method, resolved perm batch...), and the mesh spec. The
    environment signature is validated separately from the entry meta, so
    a version/device mismatch is *detected* (warned + counted), not just
    an anonymous miss."""
    return f"{autotune_key}##{constants}##{mesh_spec}"


def _abstract_sig(args) -> str:
    """Stable digest of the calling convention: tree structure + per-leaf
    (shape, dtype, weak_type). Two processes computing this for the same
    program arrive at the same string, so variants address the same
    entry."""
    import jax
    from jax.api_util import shaped_abstractify

    flat, tree = jax.tree.flatten(args)
    parts = [str(tree)]
    for a in flat:
        av = shaped_abstractify(a)
        parts.append(
            f"{av.shape}/{av.dtype}/{getattr(av, 'weak_type', False)}"
        )
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _entry_name(key: str, sig: str) -> str:
    return hashlib.sha256(f"{key}##{sig}".encode()).hexdigest()[:32]


_PYTREES_REGISTERED = False


def _register_pytree_serialization() -> None:
    """Register the custom pytree nodes that ride the engine calling
    conventions (currently :class:`~netrep_tpu.ops.stats.DiscProps`) with
    jax.export's serializer. Idempotent; a failure only disables export
    of programs carrying that node (the jit fallback is unaffected)."""
    global _PYTREES_REGISTERED
    if _PYTREES_REGISTERED:
        return
    _PYTREES_REGISTERED = True
    from jax import export as jex

    from ..ops.stats import DiscProps

    try:
        jex.register_namedtuple_serialization(
            DiscProps, serialized_name="netrep_tpu.ops.stats.DiscProps"
        )
    except ValueError:
        pass  # already registered (re-imported store in one process)


def _is_key_leaf(x) -> bool:
    import jax
    import jax.numpy as jnp

    dt = getattr(x, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jax.dtypes.prng_key)


def _to_raw_leaves(leaves, key_pos):
    import jax

    return [
        jax.random.key_data(a) if i in key_pos else a
        for i, a in enumerate(leaves)
    ]


class _Dispatcher:
    """The callable a successful :meth:`ProgramStore.acquire` returns:
    per abstract-argument signature it serves the AOT-loaded executable
    when the store has one, the shared jit fallback otherwise — so a
    tail-shaped chunk (or a bucket the warmup grid never saw) can never
    error, only compile. ``ensure`` loads-or-exports a signature without
    executing it (the warmup path)."""

    def __init__(self, store: "ProgramStore", key: str, jit_fn,
                 export_fn):
        self._store = store
        self._key = key
        self._jit = jit_fn
        self._export_fn = export_fn
        self._variants: dict[str, object] = {}
        self._missed: set[str] = set()
        self.primary_source = "jit"

    def __call__(self, *args):
        sig = _abstract_sig(args)
        fn = self._variants.get(sig)
        if fn is not None:
            return fn(*args)
        if sig not in self._missed:
            fn = self._store._load_variant(
                self._key, sig, args, self._export_fn
            )
            if fn is None and self._store.export_enabled:
                if self._store._export_variant(
                        self._key, sig, args, self._export_fn):
                    fn = self._store._load_variant(
                        self._key, sig, args, self._export_fn
                    )
            if fn is not None:
                self._variants[sig] = fn
                return fn(*args)
            self._missed.add(sig)
        return self._jit(*args)

    def ensure(self, *args) -> str:
        """Load (or, when exporting is enabled, export + load) the
        variant for this argument signature without executing it.
        Returns the resulting source class: ``aot`` when the store now
        serves this signature, ``jit`` otherwise."""
        sig = _abstract_sig(args)
        if sig in self._variants:
            return "aot"
        fn = self._store._load_variant(self._key, sig, args,
                                       self._export_fn)
        if fn is None and self._store.export_enabled:
            if self._store._export_variant(self._key, sig, args,
                                           self._export_fn):
                fn = self._store._load_variant(self._key, sig, args,
                                               self._export_fn)
        if fn is None:
            return "jit"
        self._variants[sig] = fn
        self._missed.discard(sig)
        return "aot"


class ProgramStore:
    """Fingerprinted store of serialized engine programs + an in-process
    memo of their dispatchers (the cross-process and cross-engine warm
    layers under the per-engine jit caches). Thread-safe: the serve
    preload thread and the scheduler worker share one instance."""

    def __init__(self, path: str | None = None,
                 max_bytes: int | None = None):
        self.path = path or os.environ.get(STORE_ENV) or default_dir()
        if max_bytes is None:
            try:
                max_bytes = int(float(
                    os.environ.get(MAX_MB_ENV, "512")
                ) * 1024 * 1024)
            except ValueError:
                max_bytes = 512 * 1024 * 1024
        self.max_bytes = max_bytes
        self._lock = threading.RLock()
        self._memo: dict[str, _Dispatcher] = {}
        self._export_depth = 0
        self._unexportable: set[str] = set()
        # counters (stats(); tests and the warmup CLI report them)
        self.loads = 0
        self.misses = 0
        self.exports = 0
        self.quarantined = 0

    # -- acquisition seam (the engine's single entry point) ---------------

    @property
    def export_enabled(self) -> bool:
        return (self._export_depth > 0
                or os.environ.get(EXPORT_ENV) == "1")

    def exporting(self):
        """Context manager enabling export-on-miss for the scope (the
        warmup CLI and the serve preload thread run under it)."""
        store = self

        class _Scope:
            def __enter__(self):
                with store._lock:
                    store._export_depth += 1
                return store

            def __exit__(self, *exc):
                with store._lock:
                    store._export_depth -= 1
                return False

        return _Scope()

    def acquire(self, key: str, build, *, export_fn=None,
                example_args=None):
        """The program-acquisition seam: returns ``(fn, source)`` where
        ``fn`` has the same calling convention as ``build()``'s result
        and ``source`` is ``memo`` (in-process reuse), ``aot`` (the
        primary signature deserialized from the store), or ``jit``
        (compiled as before). ``export_fn`` is the unjitted program body
        (required for export and the AOT raw-key bridge); without it —
        or without ``example_args`` — the store only memoizes."""
        with self._lock:
            disp = self._memo.get(key)
        if disp is not None:
            if (self.export_enabled and example_args is not None
                    and hasattr(disp, "ensure")):
                # an exporting scope (warmup) must persist entries even
                # for programs this process already acquired and memoized
                # — and its report shows where the entry stands, not that
                # this process happened to have run the program before
                return disp, disp.ensure(*example_args)
            return disp, "memo"
        jit_fn = build()
        if export_fn is None or example_args is None:
            with self._lock:
                self._memo_put(key, jit_fn)
            return jit_fn, "jit"
        disp = _Dispatcher(self, key, jit_fn, export_fn)
        source = disp.ensure(*example_args)
        if source == "jit" and self.export_enabled:
            # export-on-miss (warmup / NETREP_AOT_EXPORT=1): the entry is
            # written AND loaded back, so this very process already runs
            # the deserialized program — export parity is exercised at
            # export time, not first discovered by a later boot
            source = disp.ensure(*example_args)
        disp.primary_source = source
        with self._lock:
            self._memo_put(key, disp)
        return disp, source

    def _memo_put(self, key: str, fn) -> None:
        self._memo[key] = fn
        while len(self._memo) > _MEMO_MAX:
            self._memo.pop(next(iter(self._memo)))

    # -- on-disk entries ---------------------------------------------------

    def _paths(self, key: str, sig: str) -> tuple[str, str]:
        name = _entry_name(key, sig)
        return (os.path.join(self.path, name + ".bin"),
                os.path.join(self.path, name + ".json"))

    def has_entry(self, key: str, sig_args) -> bool:
        bin_path, _ = self._paths(key, _abstract_sig(sig_args))
        return os.path.exists(bin_path)

    def _quarantine(self, bin_path: str, meta_path: str,
                    reason: str) -> None:
        """A corrupt/undeserializable entry is renamed aside (``*.bad``)
        — never re-tried, never fatal, observable in ``stats()``."""
        self.quarantined += 1
        for p in (bin_path, meta_path):
            try:
                os.replace(p, p + ".bad")
            except OSError:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        _warn_once(
            f"quarantine:{reason}",
            "AOT store entry quarantined (%s): %s — the jit path "
            "compiles as before", reason, bin_path,
        )
        # a quarantined entry is a pinned anomaly (ISSUE 20): a store
        # that silently sheds entries is exactly the cold-start slip the
        # flight ring should explain after the fact
        from . import detectors

        detectors.fire("aot_refused", reason=reason, path=str(bin_path))

    def _load_variant(self, key: str, sig: str, args, export_fn):
        """One signature's entry → an executable callable, or None (plain
        absence, environment mismatch, corruption — each handled per the
        fallback ladder). On success the entry's mtime is touched (LRU)
        and the XLA compile is done eagerly here, off the first request's
        critical path, through the persistent compile cache."""
        import jax

        bin_path, meta_path = self._paths(key, sig)
        t0 = time.perf_counter()
        try:
            with open(meta_path, encoding="utf-8") as f:
                meta = json.load(f)
            if meta.get("format") != META_FORMAT:
                raise ValueError(f"meta format {meta.get('format')!r}")
        except OSError:
            return None  # plain absence: the normal cold path, no event
        except ValueError:
            self._quarantine(bin_path, meta_path, "meta-corrupt")
            self.misses += 1
            _emit("aot_store_miss", key=key, reason="corrupt")
            return None
        if meta.get("env") != env_signature():
            # written by another jax/jaxlib/device/PRNG environment:
            # silently invalid here (one-shot warning + counted miss);
            # re-exporting under this environment replaces it
            self.misses += 1
            _emit("aot_store_miss", key=key, reason="env-mismatch")
            _warn_once(
                "env-mismatch",
                "AOT store entries were written under %r (this process: "
                "%r); they are skipped and the jit path compiles as "
                "before", meta.get("env"), env_signature(),
            )
            return None
        try:
            with open(bin_path, "rb") as f:
                blob = f.read()
            from jax import export as jex

            _register_pytree_serialization()
            exported = jex.deserialize(blob)
        # netrep: allow(exception-taxonomy) — fallback-ladder boundary: ANY deserialization failure (foreign bytes, flatbuffer drift, unregistered node) must quarantine the entry and fall back to jit, never kill the run
        except Exception as e:
            self._quarantine(bin_path, meta_path,
                             f"{type(e).__name__}")
            self.misses += 1
            _emit("aot_store_miss", key=key, reason="corrupt")
            return None
        kin = frozenset(meta.get("kin") or ())
        kout = frozenset(meta.get("kout") or ())
        jitted = jax.jit(exported.call)
        flat = jax.tree.leaves(args)
        raw = _to_raw_leaves(flat, kin)
        compiled = None
        try:
            from jax.api_util import shaped_abstractify

            compiled = jitted.lower(
                *[shaped_abstractify(a) for a in raw]
            ).compile()
        # netrep: allow(exception-taxonomy) — fallback-ladder boundary: eager precompile is an optimization; any failure falls back to compile-on-first-call via the jitted wrapper
        except Exception:
            compiled = None
        try:
            os.utime(bin_path)  # LRU recency for the size-bounded GC
        except OSError:
            pass

        out_wrap = None
        if kout:
            def out_wrap(res):
                leaves, tree = jax.tree.flatten(res)
                leaves = [
                    jax.random.wrap_key_data(a) if i in kout else a
                    for i, a in enumerate(leaves)
                ]
                return jax.tree.unflatten(tree, leaves)

        state = {"compiled": compiled}

        def fn(*call_args):
            raw_leaves = _to_raw_leaves(
                jax.tree.leaves(call_args), kin
            )
            comp = state["compiled"]
            if comp is not None:
                try:
                    res = comp(*raw_leaves)
                # netrep: allow(exception-taxonomy) — fallback-ladder boundary: a sharding/layout mismatch on the precompiled fastpath drops to the jitted wrapper (same program), never to a wrong answer
                except Exception:
                    state["compiled"] = None
                    res = jitted(*raw_leaves)
            else:
                res = jitted(*raw_leaves)
            return out_wrap(res) if out_wrap is not None else res

        self.loads += 1
        _emit("aot_load", key=key, s=time.perf_counter() - t0,
              precompiled=compiled is not None,
              bytes=len(blob))
        return fn

    def _export_variant(self, key: str, sig: str, args,
                        export_fn) -> bool:
        """Trace + lower + serialize one signature of ``export_fn`` into
        the store (raw-key calling convention), then compile it once so
        the executable lands in the persistent XLA compile cache. Returns
        True on success; ANY failure marks the (key, sig) unexportable
        for this process and leaves the jit path untouched."""
        import jax

        with self._lock:
            if (key, sig) in self._unexportable:
                return False
        t0 = time.perf_counter()
        try:
            from jax import export as jex

            _register_pytree_serialization()
            flat, in_tree = jax.tree.flatten(args)
            kin = [i for i, a in enumerate(flat) if _is_key_leaf(a)]
            out_shape = jax.eval_shape(export_fn, *args)
            out_leaves = jax.tree.leaves(out_shape)
            kout = [i for i, a in enumerate(out_leaves)
                    if _is_key_leaf(a)]
            kin_set, kout_set = frozenset(kin), frozenset(kout)

            def raw_fn(*raw_leaves):
                leaves = [
                    jax.random.wrap_key_data(a) if i in kin_set else a
                    for i, a in enumerate(raw_leaves)
                ]
                res = export_fn(*jax.tree.unflatten(in_tree, leaves))
                if kout_set:
                    rl, rt = jax.tree.flatten(res)
                    rl = [
                        jax.random.key_data(a) if i in kout_set else a
                        for i, a in enumerate(rl)
                    ]
                    res = jax.tree.unflatten(rt, rl)
                return res

            raw = _to_raw_leaves(flat, kin_set)
            from jax.api_util import shaped_abstractify

            raw_abs = [shaped_abstractify(a) for a in raw]
            exported = jex.export(jax.jit(raw_fn))(*raw_abs)
            blob = exported.serialize()
            bin_path, meta_path = self._paths(key, sig)
            os.makedirs(self.path, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".bin.tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, bin_path)
            meta = {
                "format": META_FORMAT, "key": key, "sig": sig,
                "env": env_signature(), "kin": sorted(kin),
                "kout": sorted(kout), "created": time.time(),
                "bytes": len(blob),
            }
            fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".json.tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, meta_path)
            # compile once NOW: the executable lands in the persistent
            # XLA compile cache, so a warm process's eager precompile at
            # load time is a cache read, not a compile
            jax.jit(jex.deserialize(blob).call).lower(*raw_abs).compile()
        # netrep: allow(exception-taxonomy) — fallback-ladder boundary: export of an unexportable program (pallas interpret callbacks, unregistered pytree, OSError on a read-only store) must leave the jit path untouched, never kill the run
        except Exception as e:
            with self._lock:
                self._unexportable.add((key, sig))
            _warn_once(
                f"export:{type(e).__name__}",
                "AOT export failed (%s: %s); the program stays on the "
                "jit path", type(e).__name__, e,
            )
            return False
        self.exports += 1
        _emit("aot_export", key=key, s=time.perf_counter() - t0,
              bytes=len(blob))
        self.gc()
        return True

    # -- hygiene -----------------------------------------------------------

    def gc(self) -> int:
        """Size-bounded LRU GC: quarantined ``*.bad`` files go first,
        then the least-recently-used entries beyond ``max_bytes``.
        Returns the number of files removed. Best-effort — an unlistable
        store directory disables nothing but the bound."""
        removed = 0
        try:
            names = os.listdir(self.path)
        except OSError:
            return 0
        for n in names:
            if n.endswith(".bad"):
                try:
                    os.unlink(os.path.join(self.path, n))
                    removed += 1
                except OSError:
                    pass
        entries = []
        total = 0
        for n in names:
            if not n.endswith(".bin"):
                continue
            p = os.path.join(self.path, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
            total += st.st_size
        entries.sort()  # oldest first
        for _mt, size, p in entries:
            if total <= self.max_bytes:
                break
            for q in (p, p[:-4] + ".json"):
                try:
                    os.unlink(q)
                    removed += 1
                except OSError:
                    pass
            total -= size
        return removed

    def stats(self) -> dict:
        n, total = 0, 0
        try:
            for name in os.listdir(self.path):
                if name.endswith(".bin"):
                    n += 1
                    try:
                        total += os.stat(
                            os.path.join(self.path, name)
                        ).st_size
                    except OSError:
                        pass
        except OSError:
            pass
        return {
            "path": self.path, "entries": n, "bytes": total,
            "loads": self.loads, "misses": self.misses,
            "exports": self.exports, "quarantined": self.quarantined,
            "memo": len(self._memo),
        }


_STORE: ProgramStore | None = None
_STORE_LOCK = threading.Lock()


def get_store() -> ProgramStore | None:
    """The process-wide store singleton, or None when ``NETREP_AOT=0``
    (every acquisition then jits exactly as before the store existed)."""
    if os.environ.get(DISABLE_ENV) == "0":
        return None
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            _STORE = ProgramStore()
        return _STORE


def reset_store() -> None:
    """Drop the singleton (tests re-point ``NETREP_AOT_STORE`` between
    cases; a long-lived process never needs this)."""
    global _STORE
    with _STORE_LOCK:
        _STORE = None
