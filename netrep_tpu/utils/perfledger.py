"""Append-only per-run throughput ledger + regression check (ISSUE 5).

Five PRs of BENCH_r0*.json trajectory accumulated with nothing consuming
it — a 129→10 perms/s CPU-fallback collapse and a 120 s silent probe hang
sat in prose no tool would ever flag. This module is the consumer: every
measured run appends one JSON line (a *throughput fingerprint* — backend,
problem-shape key, mode, perms/s, compile estimate), and
``python -m netrep_tpu perf <ledger> --check`` compares the newest entry
against the robust median of its *matching* history (same fingerprint)
and exits non-zero when it regressed beyond the threshold — a CI gate,
not a prose warning.

Writers:

- the engine null loops (:func:`maybe_record_run`), for any
  telemetry-enabled run, when ``NETREP_PERF_LEDGER`` names a path;
- ``bench.py`` (every metric row carrying ``perms_per_sec``);
- ``benchmarks/tpu_watch.sh`` (exports ``NETREP_PERF_LEDGER`` and runs
  the check after each step);
- ``perf --ingest BENCH_r0*.json`` — converts the repo's driver-bench
  history so five PRs of trajectory become the initial baseline.

Entries are one JSON object per line, keyed ``perf_v`` (so a ledger can
share a file with telemetry events or bench rows without ambiguity):

    {"perf_v": 1, "t": <unix s>, "source": "run"|"bench"|"ingest",
     "round": <int|None>, "run": <run id|None>, "fingerprint": <str>,
     "backend": <str>, "mode": <str|None>, "perms_per_sec": <float>,
     "compile_s": <float|None>, "n_perm": <int|None>, "metric": <str|None>}

``fingerprint`` is the grouping identity: the engine's autotune/compile
-cache key for run entries, a normalized (metric, backend-class, chunk,
dtype) tuple for bench rows — entries only ever compare against history
of the same fingerprint, so a CPU-fallback row can never be judged
against TPU history. Appends are best-effort (an unwritable ledger warns
once and never fails the run), reads are tolerant (foreign lines are
skipped).
"""

from __future__ import annotations

import json
import logging
import os
import time

logger = logging.getLogger("netrep_tpu")

#: entry-line format version (bump deliberately, with the golden test)
ENTRY_VERSION = 1

#: env var naming the ledger path — set by tpu_watch.sh; any
#: telemetry-enabled run and every bench row appends when it is set
LEDGER_ENV = "NETREP_PERF_LEDGER"

#: default regression threshold: newest/median < (1 - threshold) fails.
#: 0.4 tolerates the measured box-contention drift of the CPU-fallback
#: rows (752→982 s across rounds with no code change) while still
#: catching a 2× regression outright.
DEFAULT_THRESHOLD = 0.4

#: how many most-recent matching entries the median is taken over
DEFAULT_WINDOW = 8

_APPEND_WARNED = False


def default_path() -> str:
    """Ledger path resolution shared by the CLI and the writers: the
    ``NETREP_PERF_LEDGER`` env var, else ``netrep_perf_ledger.jsonl`` in
    the CWD."""
    return os.environ.get(LEDGER_ENV) or os.path.join(
        os.getcwd(), "netrep_perf_ledger.jsonl"
    )


#: version of the OPTIONAL per-tenant attributed-cost block a ledger
#: entry may carry (ISSUE 13): ``{"cost_v": 1, "cost": {tenant:
#: {device_s, perms, bytes_to_host}}}`` appended after the pinned base
#: keys — the fleet-admission signal (ROADMAP item 1) rides the same
#: ledger the brownout estimator already reads. Entries without costs
#: keep the exact PR 5 key order (golden-shape test unchanged).
COST_VERSION = 1

#: version of the OPTIONAL roofline block a ledger entry may carry
#: (ISSUE 18): ``{"roofline_v": 1, "roofline": {family, flops_per_perm,
#: bytes_per_perm, flops, bytes_hbm, device_kind, peak_flops, peak_bw,
#: sol_pps, achieved_pps, utilisation}}`` appended after the pinned base
#: keys (and after any ``cost`` block) — the measured speed-of-light
#: record ``roofline --ledger --check`` gates on. Entries without it keep
#: the exact PR 5 key order (golden-shape test unchanged).
ROOFLINE_VERSION = 1


def make_entry(
    fingerprint: str,
    perms_per_sec: float,
    source: str,
    backend: str = "",
    mode: str | None = None,
    compile_s: float | None = None,
    n_perm: int | None = None,
    run_id: str | None = None,
    round_n: int | None = None,
    metric: str | None = None,
    t: float | None = None,
    cost: dict | None = None,
    roofline: dict | None = None,
) -> dict:
    """One ledger line, in pinned key order (golden-shape test); the
    optional ``cost`` rollup appends ``cost_v``/``cost`` and the optional
    ``roofline`` block appends ``roofline_v``/``roofline`` after the base
    keys so measurement-carrying rows extend the schema without
    disturbing it."""
    entry = {
        "perf_v": ENTRY_VERSION,
        "t": float(t) if t is not None else time.time(),
        "source": str(source),
        "round": int(round_n) if round_n is not None else None,
        "run": run_id,
        "fingerprint": str(fingerprint),
        "backend": str(backend),
        "mode": mode,
        "perms_per_sec": round(float(perms_per_sec), 4),
        "compile_s": (
            round(float(compile_s), 4) if compile_s is not None else None
        ),
        "n_perm": int(n_perm) if n_perm is not None else None,
        "metric": metric,
    }
    if cost is not None:
        entry["cost_v"] = COST_VERSION
        entry["cost"] = cost
    if roofline is not None:
        entry["roofline_v"] = ROOFLINE_VERSION
        entry["roofline"] = roofline
    return entry


def append_entry(entry: dict, path: str | None = None) -> bool:
    """Append one entry (flushed line). Best-effort: an unwritable ledger
    warns once per process and returns False — recording a measurement
    must never fail the run that produced it."""
    global _APPEND_WARNED
    path = path or default_path()
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry) + "\n")
        return True
    except OSError as e:
        if not _APPEND_WARNED:
            _APPEND_WARNED = True
            logger.warning("perf ledger %r not writable (%s: %s); "
                           "throughput entries are dropped", path,
                           type(e).__name__, e)
        return False


def read_entries(path: str) -> list[dict]:
    """All ledger entries in file order, skipping foreign/corrupt lines
    (the ledger may share a file with bench rows or telemetry events)."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (isinstance(row, dict) and row.get("perf_v") == ENTRY_VERSION
                    and isinstance(row.get("fingerprint"), str)
                    and isinstance(row.get("perms_per_sec"), (int, float))):
                out.append(row)
    return out


def maybe_record_run(
    fingerprint: str,
    perms_per_sec: float,
    mode: str,
    backend: str,
    compile_s: float | None = None,
    n_perm: int | None = None,
    run_id: str | None = None,
    roofline: dict | None = None,
) -> bool:
    """Engine-loop hook: append a run entry when ``NETREP_PERF_LEDGER``
    names a ledger; silently a no-op otherwise (the env-gated contract —
    telemetry-on runs pay one getenv)."""
    path = os.environ.get(LEDGER_ENV)
    if not path or not perms_per_sec > 0:
        return False
    return append_entry(
        make_entry(fingerprint, perms_per_sec, "run", backend=backend,
                   mode=mode, compile_s=compile_s, n_perm=n_perm,
                   run_id=run_id, roofline=roofline),
        path,
    )


# ---------------------------------------------------------------------------
# bench-row conversion + BENCH_r0*.json ingestion
# ---------------------------------------------------------------------------


def _backend_class(device: str) -> str:
    d = device.lower()
    if "tpu" in d:
        return "tpu"
    if "cpu" in d:
        return "cpu"
    if "gpu" in d or "cuda" in d:
        return "gpu"
    return device or "unknown"


def bench_fingerprint(row: dict) -> str | None:
    """Grouping identity of a bench metric row: the metric label up to its
    parenthesized config note / fallback suffix, plus backend class,
    chunk, and dtype — so r01's TPU north row and r05's CPU-fallback north
    row form two histories that never compare against each other."""
    metric = row.get("metric")
    if not isinstance(metric, str) or not metric:
        return None
    base = metric.split(" [", 1)[0].split(" (", 1)[0].strip()
    parts = [f"bench|{base}", _backend_class(str(row.get("device", "")))]
    if row.get("chunk") is not None:
        parts.append(f"chunk:{row['chunk']}")
    if row.get("dtype"):
        parts.append(f"dtype:{row['dtype']}")
    return "|".join(parts)


def entry_from_bench_row(row: dict, source: str = "bench",
                         round_n: int | None = None,
                         t: float | None = None,
                         mode: str = "bench") -> dict | None:
    """Bench metric row → ledger entry, or None for rows without a
    throughput number (warning/error/skip rows). ``source``/``mode``
    default to the bench path; the serve load generator passes
    ``source="serve"`` so serving-path rows form their own provenance
    class in the ledger. Serve-path fingerprints split two ways (ISSUE
    7): the load generator's rows through THIS function (metric label
    carries the traffic shape), and the engine-run entries from packed
    serve runs via :func:`maybe_record_run` — whose fingerprint is the
    packed engine's ``autotune_key`` carrying a ``packed:<G>`` extra, so
    packed-dispatch throughput never shares a regression history with
    the stand-alone engine of the same bucket signature."""
    pps = row.get("perms_per_sec")
    if not isinstance(pps, (int, float)) or not pps > 0:
        return None
    fp = bench_fingerprint(row)
    if fp is None:
        return None
    return make_entry(
        fp, pps, source, backend=_backend_class(str(row.get("device", ""))),
        mode=mode, run_id=row.get("telemetry"),
        metric=str(row.get("metric"))[:160], round_n=round_n, t=t,
        cost=row.get("cost") if isinstance(row.get("cost"), dict) else None,
        roofline=(row.get("roofline")
                  if isinstance(row.get("roofline"), dict) else None),
    )


def ingest_bench_files(paths, ledger_path: str) -> int:
    """Convert driver BENCH_r0*.json files (``{"n", "cmd", "tail",
    "parsed"}``) into ledger entries, ordered by round then line order —
    every JSON line found in ``tail`` plus the ``parsed`` row, de-duped.
    Returns the number of entries appended."""
    files = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("skipping %r: %s", p, e)
            continue
        files.append((doc.get("n") if isinstance(doc, dict) else None,
                      p, doc))
    files.sort(key=lambda x: (x[0] is None, x[0] if x[0] is not None else 0,
                              x[1]))
    n_added = 0
    for round_n, _p, doc in files:
        if not isinstance(doc, dict):
            continue
        rows, seen = [], set()
        for line in str(doc.get("tail", "")).splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict):
                rows.append(row)
                seen.add(json.dumps(row, sort_keys=True))
        parsed = doc.get("parsed")
        if (isinstance(parsed, dict)
                and json.dumps(parsed, sort_keys=True) not in seen):
            rows.append(parsed)
        for row in rows:
            # synthetic, strictly ordered timestamps: the driver files
            # carry no wall time, but check() keys on append order anyway
            entry = entry_from_bench_row(
                row, source="ingest", round_n=round_n,
                t=float(round_n or 0),
            )
            if entry is not None and append_entry(entry, ledger_path):
                n_added += 1
    return n_added


# ---------------------------------------------------------------------------
# trend + regression check
# ---------------------------------------------------------------------------


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def check(path: str, threshold: float = DEFAULT_THRESHOLD,
          window: int = DEFAULT_WINDOW) -> tuple[bool, str]:
    """Compare the ledger's NEWEST entry against the robust median of the
    prior entries sharing its fingerprint (most recent ``window`` of
    them). Returns ``(ok, report)``:

    - no entries → ok (nothing to judge);
    - no matching history → ok, noted (first measurement of this
      fingerprint — a baseline, not a regression);
    - ratio newest/median < 1 - threshold → **not ok** (the CLI exits
      non-zero; ``tpu_watch.sh`` surfaces it after each step).
    """
    entries = read_entries(path)
    if not entries:
        return True, f"perf ledger {path!r}: no entries"
    newest = entries[-1]
    fp = newest["fingerprint"]
    priors = [e for e in entries[:-1] if e["fingerprint"] == fp]
    priors = priors[-int(window):]
    head = (
        f"newest: {newest['perms_per_sec']:g} perms/s "
        f"[{newest.get('source')}] {fp}"
    )
    if not priors:
        return True, (
            f"{head}\nno prior entries with this fingerprint — recorded "
            "as the baseline"
        )
    med = _median([float(e["perms_per_sec"]) for e in priors])
    ratio = float(newest["perms_per_sec"]) / med if med > 0 else 1.0
    body = (
        f"{head}\nhistory: {len(priors)} matching entr"
        f"{'y' if len(priors) == 1 else 'ies'}, median {med:g} perms/s "
        f"→ ratio {ratio:.3f} (fail below {1.0 - threshold:.2f})"
    )
    if ratio < 1.0 - threshold:
        return False, (
            f"{body}\nPERF REGRESSION: the newest entry is "
            f"{(1.0 - ratio) * 100.0:.0f}% below its history's median"
        )
    return True, f"{body}\nOK"


def _roofline_signal(entry: dict) -> tuple[str, float] | None:
    """The gauged quantity of a roofline-bearing entry: ``("utilisation",
    u)`` when the device's speed of light is known, else
    ``("achieved_pps", pps)`` so CPU mechanism runs (utilisation null —
    never a guess) still form a checkable history. Returns None for
    entries without a roofline block or without a positive signal."""
    rb = entry.get("roofline")
    if not isinstance(rb, dict):
        return None
    util = rb.get("utilisation")
    if isinstance(util, (int, float)) and util > 0:
        return "utilisation", float(util)
    pps = rb.get("achieved_pps")
    if isinstance(pps, (int, float)) and pps > 0:
        return "achieved_pps", float(pps)
    return None


def check_roofline(path: str, threshold: float = DEFAULT_THRESHOLD,
                   window: int = DEFAULT_WINDOW) -> tuple[bool, str]:
    """Speed-of-light drift gate (ISSUE 18): compare the NEWEST
    roofline-bearing entry's utilisation against the robust median of the
    prior roofline entries sharing its fingerprint (most recent
    ``window``). Same contract shape as :func:`check`:

    - no roofline entries → ok (nothing to judge);
    - no matching history → ok, noted (baseline);
    - newest and priors judged on utilisation when the peak table knows
      the device, on achieved_pps otherwise (CPU/unknown kinds) — priors
      whose signal kind differs from the newest's are skipped, so a CPU
      mechanism row never gates against TPU utilisation history;
    - ratio newest/median < 1 - threshold → **not ok** (CLI exits 2).
    """
    entries = [e for e in read_entries(path)
               if _roofline_signal(e) is not None]
    if not entries:
        return True, f"perf ledger {path!r}: no roofline entries"
    newest = entries[-1]
    kind, val = _roofline_signal(newest)
    fp = newest["fingerprint"]
    fam = (newest.get("roofline") or {}).get("family")
    priors = []
    for e in entries[:-1]:
        if e["fingerprint"] != fp:
            continue
        k, v = _roofline_signal(e)
        if k == kind:
            priors.append(v)
    priors = priors[-int(window):]
    head = (
        f"newest roofline: {kind}={val:g} "
        f"[family={fam}] {fp}"
    )
    if not priors:
        return True, (
            f"{head}\nno prior roofline entries with this fingerprint — "
            "recorded as the baseline"
        )
    med = _median(priors)
    ratio = val / med if med > 0 else 1.0
    body = (
        f"{head}\nhistory: {len(priors)} matching entr"
        f"{'y' if len(priors) == 1 else 'ies'}, median {kind} {med:g} "
        f"→ ratio {ratio:.3f} (fail below {1.0 - threshold:.2f})"
    )
    if ratio < 1.0 - threshold:
        return False, (
            f"{body}\nROOFLINE REGRESSION: the newest entry's {kind} is "
            f"{(1.0 - ratio) * 100.0:.0f}% below its history's median"
        )
    return True, f"{body}\nOK"


def trend(path: str) -> str:
    """Per-fingerprint trend table of the whole ledger (the no-``--check``
    CLI view): entry count, median, newest, and newest/median ratio."""
    entries = read_entries(path)
    if not entries:
        return f"perf ledger {path!r}: no entries"
    groups: dict[str, list[dict]] = {}
    order: list[str] = []
    for e in entries:
        fp = e["fingerprint"]
        if fp not in groups:
            groups[fp] = []
            order.append(fp)
        groups[fp].append(e)
    lines = [f"perf ledger {path!r}: {len(entries)} entries, "
             f"{len(order)} fingerprint(s)"]
    for fp in order:
        g = groups[fp]
        vals = [float(e["perms_per_sec"]) for e in g]
        med = _median(vals)
        last = vals[-1]
        ratio = last / med if med > 0 else float("nan")
        lines.append(
            f"  {fp}\n    n={len(g)}  median={med:g}  newest={last:g}  "
            f"newest/median={ratio:.3f}"
        )
    return "\n".join(lines)
