"""Black-box flight recorder (ISSUE 20): an always-on, bounded, in-memory
ring of recent telemetry events.

Every observability plane before this one (JSONL telemetry, spans,
tracing, roofline) is opt-in and OFF by default, so the minutes before an
incident — a stall escalation, a replica death mid-pack, a drift verdict
in a watch cycle — were simply gone. This module closes that gap with two
pieces that ride the existing ambient-bus seam without touching the
engine's hot loop:

- :class:`FlightRecorder` — a deterministic ring of serialized event
  lines bounded by BOTH an entry count and a byte budget
  (``NETREP_FLIGHTREC_ENTRIES`` / ``NETREP_FLIGHTREC_BYTES``): append one,
  evict oldest-first until both bounds hold again, never below one entry.
  It is fed by the process-wide flight observer hook
  (:func:`netrep_tpu.utils.telemetry.set_flight_observer`), which fires
  for every event emitted on ANY bus — so the ring captures a run's chunk
  beats, span opens/closes, and gauges even when no JSONL sink exists.

- :class:`FlightBus` — a sink-less :class:`~netrep_tpu.utils.telemetry.
  Telemetry` installed at the BOTTOM of the ambient stack by
  :func:`install` (package import does this once; ``NETREP_FLIGHTREC=0``
  opts out). ``resolve()``/``current()`` therefore return it only when no
  user bus is active — an explicit or activated bus still wins (innermost
  = last), so every existing telemetry contract is preserved. The bus is
  marked ``flight_only = True``; the engine uses that flag to keep
  flight-only runs out of the perf ledger, the roofline note, and the
  device-memory probe, which keeps recorder-on runs bit-identical to
  recorder-off runs (host-side capture only — nothing device-side ever
  depends on the recorder).

The ring is drained into ``flight_ring.jsonl`` by a diagnostic bundle
(:mod:`netrep_tpu.utils.bundle`) — the black box a post-incident session
reads first.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

from . import telemetry as tm

#: master opt-out: ``NETREP_FLIGHTREC=0`` disables install() entirely
ENV_TOGGLE = "NETREP_FLIGHTREC"
#: ring entry bound override (int > 0)
ENV_ENTRIES = "NETREP_FLIGHTREC_ENTRIES"
#: ring byte bound override (int > 0; bytes of serialized JSONL)
ENV_BYTES = "NETREP_FLIGHTREC_BYTES"

#: default bounds: enough for several minutes of chunk beats around an
#: incident while staying invisible in a long-lived server's RSS
DEFAULT_ENTRIES = 2048
DEFAULT_BYTES = 2 << 20


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class FlightRecorder:
    """Bounded ring of serialized telemetry event lines.

    Entries are stored as the JSON text that would have hit a JSONL sink,
    so byte accounting is exact and a bundle dump is a straight write.
    Eviction is deterministic: strictly oldest-first, until both the
    entry bound and the byte bound hold, but never below one entry (the
    newest event is always retained even if it alone exceeds the byte
    budget). Thread-safe — the observer hook fires from whatever thread
    emitted the event."""

    def __init__(self, max_entries: int | None = None,
                 max_bytes: int | None = None):
        self.max_entries = (max_entries if max_entries is not None
                            else _env_int(ENV_ENTRIES, DEFAULT_ENTRIES))
        self.max_bytes = (max_bytes if max_bytes is not None
                          else _env_int(ENV_BYTES, DEFAULT_BYTES))
        if self.max_entries < 1 or self.max_bytes < 1:
            raise ValueError("flight ring bounds must be >= 1")
        self._lock = threading.Lock()
        self._ring: deque[str] = deque()
        self._bytes = 0
        self.n_seen = 0
        self.n_evicted = 0

    def record(self, record: dict) -> None:
        """Append one event record (already-shaped telemetry dict)."""
        try:
            line = json.dumps(record, default=tm._json_default)
        except (TypeError, ValueError):
            return  # an unserializable observer payload is not worth a crash
        nb = len(line.encode("utf-8", errors="replace"))
        with self._lock:
            self.n_seen += 1
            self._ring.append(line)
            self._bytes += nb
            while len(self._ring) > 1 and (
                len(self._ring) > self.max_entries
                or self._bytes > self.max_bytes
            ):
                old = self._ring.popleft()
                self._bytes -= len(old.encode("utf-8", errors="replace"))
                self.n_evicted += 1

    def lines(self) -> list[str]:
        """Ring contents, oldest first, as serialized JSON lines."""
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> list[dict]:
        """Ring contents, oldest first, as parsed event dicts."""
        return [json.loads(line) for line in self.lines()]

    def dump_jsonl(self, path: str) -> int:
        """Write the ring to ``path`` as JSONL; returns entries written."""
        lines = self.lines()
        with open(path, "w", encoding="utf-8") as f:
            for line in lines:
                f.write(line + "\n")
        return len(lines)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._ring),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "n_seen": self.n_seen,
                "n_evicted": self.n_evicted,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._bytes = 0


class FlightBus(tm.Telemetry):
    """Sink-less ambient bus the recorder installs at the bottom of the
    telemetry stack: runs that would otherwise resolve no bus resolve
    this one, so their events reach the flight ring (via the observer)
    instead of vanishing. ``flight_only`` marks it for the engine's
    accounting gates — a flight-only run must never write perf-ledger
    history or the process roofline note."""

    flight_only = True

    def __init__(self):
        super().__init__(path=None, run_id="flight")


#: module singletons managed by install()/uninstall()
_RECORDER: FlightRecorder | None = None
_BUS: FlightBus | None = None


def enabled() -> bool:
    """Whether the always-on recorder is allowed in this process."""
    return os.environ.get(ENV_TOGGLE, "1") != "0"


def _observe(bus: tm.Telemetry, record: dict) -> None:
    """Process-wide flight observer: ring capture + anomaly scan."""
    rec = _RECORDER
    if rec is None:
        return
    rec.record(record)
    from . import detectors

    detectors.scan(bus, record)


def install() -> FlightRecorder | None:
    """Install the always-on recorder (idempotent): create the ring,
    register the flight observer, and seat the :class:`FlightBus` at the
    bottom of the ambient stack. Called once at package import; returns
    the recorder, or None when ``NETREP_FLIGHTREC=0`` opted out."""
    global _RECORDER, _BUS
    if not enabled():
        return None
    if _RECORDER is not None:
        return _RECORDER
    _RECORDER = FlightRecorder()
    _BUS = FlightBus()
    tm._ACTIVE.insert(0, _BUS)
    tm.set_flight_observer(_observe)
    return _RECORDER


def uninstall() -> None:
    """Tear the recorder down (tests; also the bit-identity drill's
    recorder-off arm)."""
    global _RECORDER, _BUS
    tm.set_flight_observer(None)
    if _BUS is not None and _BUS in tm._ACTIVE:
        tm._ACTIVE.remove(_BUS)
    _RECORDER = None
    _BUS = None


def recorder() -> FlightRecorder | None:
    """The installed ring, or None when the recorder is off."""
    return _RECORDER


def bus() -> FlightBus | None:
    """The installed ambient flight bus, or None when the recorder is
    off."""
    return _BUS
