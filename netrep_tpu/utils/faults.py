"""Fault-tolerant null execution (ISSUE 4).

The reference run is all-or-nothing and the north-star backends
(tunneled/preemptible TPU) make failures the common case: gRPC deadlines,
dropped tunnels, hung dispatches, lost devices. PRs 1-3 built the
primitives that make recovery *provably exact* — the ``fold_in(key, i)``
per-permutation RNG contract (re-dispatching chunk *i* regenerates
identical keys), ``resume == uninterrupted`` checkpoints, and the
telemetry stall watchdog. This module turns those primitives into a
recovery ladder around every null loop:

1. **Retry with backoff** — a dispatch failure classified *transient*
   (:func:`classify_error`) is re-dispatched after exponential backoff
   with deterministic jitter, up to ``FaultPolicy.max_retries`` times.
   Exact by construction: the retried chunk draws the same permutations.
2. **Abandon a hung dispatch** — with ``hang_timeout_s`` set (or the
   stall watchdog's warn→act escalation wired), dispatches run on a
   worker thread; a dispatch that neither returns nor errors is
   *abandoned* (``chunk_abandoned`` event), completed work is
   checkpointed, and the chunk is re-dispatched. More than
   ``max_abandons`` abandonments escalates to the device-loss ladder.
3. **Shrink the mesh** (ISSUE 6) — a device-loss-class failure that left
   *survivors* (:func:`netrep_tpu.utils.backend.enumerate_survivors`)
   rebuilds a smaller mesh from the surviving devices and resumes from
   the checkpoint on it, instead of falling off the CPU cliff. Exact by
   the same contract: per-permutation keys depend only on ``(key,
   index)``, so the re-bucketed permutation slices draw identical
   permutations on any mesh shape.
4. **Grow the mesh back** — when capacity returns (the injected
   ``capacity_restored`` plan kind, or an external monitor calling
   :meth:`FaultRuntime.request_grow`), the null loop raises
   :class:`CapacityRestoredError` at the next chunk/superchunk boundary
   — after committing and checkpointing — and the API layer rebuilds
   the engine over the restored full device set and resumes.
5. **Degrade to CPU** — the FINAL rung, taken only when zero
   accelerator devices survive: :class:`DeviceLostError` propagates
   past the loop's failure-save hook (which checkpoints all completed
   permutations first); the API layer (``models/preservation.py``) then
   forces the CPU platform
   (:func:`netrep_tpu.utils.backend.degrade_to_cpu`), rebuilds the
   engine, and resumes from the checkpoint — bit-identically, because
   per-permutation keys depend only on ``(key, index)``.

Everything is driven by :class:`FaultPolicy`
(:mod:`netrep_tpu.utils.config`), surfaced as
``module_preservation(fault_policy=...)``. Disabled (the default), the
loops pay one ``None`` check per run and are bit-identical to previous
releases.

**Fault injection.** Every recovery path is tested, not trusted: a
:class:`FaultInjector` raises chosen error classes at chosen permutation
boundaries from a deterministic plan. Plans are compact strings —
``"transient@128"`` (fail the dispatch covering permutation 128 once),
``"transient@128x3"`` (three successive attempts), ``"device_lost@64"``,
``"device_lost_partial@64"`` (half the mesh's devices die; survivors
remain — the mesh-shrink rung), ``"capacity_restored@96"`` (the lost
capacity comes back; the loop grows the mesh at the next boundary),
``"hang@192"``, ``"interrupt@96"``, ``"fatal@32"``, ``"crash@64"`` (an
uncatchable in-process crash — the serving tier-1 stand-in for a
``SIGKILL``: it unwinds past every recovery handler so failure-saves
fire but nothing recovers in-process), ``"sigkill@64"`` (the real thing:
``os.kill(getpid(), SIGKILL)`` — the ``chaos --serve`` drill's
plan-injected kill point; nothing after the chosen dispatch runs, not
even a failure-save, so recovery proves the *periodic* durability story)
— joined with ``;``,
set via ``FaultPolicy(plan=...)`` or the ``NETREP_FAULT_PLAN`` env var
(which also *activates* a default policy, for bench/CI runs). Injection
state lives on the :class:`FaultRuntime`, which survives engine rebuilds
within one ``module_preservation`` call — so an injected device loss
fires once, not again on the degraded resume.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable

from .config import FaultPolicy

logger = logging.getLogger("netrep_tpu")

__all__ = [
    "FaultPolicy",
    "FaultRuntime",
    "FaultInjector",
    "FaultSpec",
    "CapacityRestoredError",
    "DeviceLostError",
    "SimulatedCrash",
    "DispatchAbandonedError",
    "InjectedTransientError",
    "InjectedDeviceLost",
    "InjectedPartialDeviceLost",
    "InjectedFatalError",
    "classify_error",
    "parse_plan",
    "backoff_delay",
    "resolve_runtime",
]

#: env var holding a fault plan; when set it also ACTIVATES a default
#: FaultPolicy for runs that passed fault_policy=None (bench/CI injection)
PLAN_ENV = "NETREP_FAULT_PLAN"


# ---------------------------------------------------------------------------
# Error taxonomy
# ---------------------------------------------------------------------------


class InjectedTransientError(RuntimeError):
    """Injected stand-in for a retryable backend failure (gRPC deadline,
    dropped tunnel packet) — classified ``transient``."""


class InjectedDeviceLost(RuntimeError):
    """Injected stand-in for a lost/preempted device — classified
    ``device_lost``."""


class InjectedPartialDeviceLost(InjectedDeviceLost):
    """Injected PARTIAL device loss: some of the mesh's devices die but
    survivors remain — the mesh-shrink rung's stand-in. ``n_lost`` is the
    number of lost devices, or None for "half the current mesh" (the
    deterministic drill default;
    :func:`netrep_tpu.utils.backend.enumerate_survivors` resolves it
    against the actual mesh)."""

    def __init__(self, msg: str, n_lost: int | None = None):
        super().__init__(msg)
        self.n_lost = n_lost


class CapacityRestoredError(Exception):
    """Control-flow signal, not a failure: lost device capacity is back,
    and the null loop should stop at the next chunk/superchunk boundary —
    after committing and checkpointing — so the API layer can rebuild the
    engine over the restored mesh and resume. Raised only by
    :meth:`FaultRuntime.check_grow` on runs that have a checkpoint to
    resume from."""


class InjectedFatalError(RuntimeError):
    """Injected stand-in for a genuine bug-class failure — never retried."""


class SimulatedCrash(BaseException):
    """In-process stand-in for a ``SIGKILL`` (plan kind ``crash``): a
    *BaseException* so it unwinds past every ``except Exception`` recovery
    handler — the loops' failure-save hooks still fire (modeling the
    periodic checkpoint that existed at kill time), but nothing retries,
    degrades, or reports; the thread that hit it is simply gone. The
    serving tier-1 kill→recover drill uses it because a test process
    cannot SIGKILL itself (the real signal rides the ``sigkill`` kind in
    the ``chaos --serve`` subprocess drill)."""


class DispatchAbandonedError(RuntimeError):
    """A hung dispatch was abandoned (timeout or watchdog escalation);
    classified ``transient`` so the normal retry ladder re-dispatches."""


class DeviceLostError(RuntimeError):
    """Raised to the API layer when the run should degrade to CPU: a
    device-loss-class failure (``reason='device_lost'``), transient
    retries exhausted (``'retries_exhausted'`` — a backend that fails
    every re-dispatch is as gone as a lost device), or too many hung
    dispatches (``'abandons_exhausted'``). The loop's failure-save hook
    has already checkpointed every completed permutation when this
    propagates."""

    def __init__(self, msg: str, reason: str = "device_lost"):
        super().__init__(msg)
        self.reason = reason


#: lowercase substrings of ``"TypeName: message"`` that mark a failure as
#: retryable — the gRPC/tunnel vocabulary of the axon backend's transport
#: errors (utils/backend.py documents the failure modes)
_TRANSIENT_MARKERS = (
    "deadline exceeded",
    "deadline_exceeded",
    "unavailable",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "socket closed",
    "stream removed",
    "transport closed",
    "too many pings",
    "recvmsg",
    "temporarily",
)

#: markers of a lost/preempted device — not retryable in place; the
#: degradation ladder (emergency checkpoint → CPU rebuild → resume) applies
_DEVICE_LOSS_MARKERS = (
    "device lost",
    "lost device",
    "device is lost",
    "device failure",
    "device disconnected",
    "chip has been lost",
    "preempted",
    "halted",
)


def classify_error(exc: BaseException) -> str:
    """``'transient'`` (retry in place), ``'device_lost'`` (degradation
    ladder), or ``'fatal'`` (propagate — the default, so genuine bugs are
    never silently retried). Classification keys on exception type first
    (injected faults, connection errors), then on the lowercased
    ``"TypeName: message"`` text, because JAX surfaces backend failures as
    generic ``XlaRuntimeError``/``RuntimeError`` with a status-code
    message."""
    if isinstance(exc, (InjectedTransientError, DispatchAbandonedError)):
        return "transient"
    if isinstance(exc, InjectedDeviceLost):
        return "device_lost"
    if isinstance(exc, InjectedFatalError):
        return "fatal"
    text = f"{type(exc).__name__}: {exc}".lower()
    if any(m in text for m in _DEVICE_LOSS_MARKERS):
        return "device_lost"
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return "transient"
    if any(m in text for m in _TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


# ---------------------------------------------------------------------------
# Fault plans (deterministic injection harness)
# ---------------------------------------------------------------------------

_KINDS = ("transient", "device_lost", "device_lost_partial",
          "capacity_restored", "fatal", "hang", "interrupt", "crash",
          "sigkill")

_RAISERS = {
    "transient": lambda spec: InjectedTransientError(
        f"injected transient fault at permutation {spec.at_perm}"
    ),
    "device_lost": lambda spec: InjectedDeviceLost(
        f"injected device loss at permutation {spec.at_perm}"
    ),
    "device_lost_partial": lambda spec: InjectedPartialDeviceLost(
        f"injected partial device loss at permutation {spec.at_perm}"
    ),
    "fatal": lambda spec: InjectedFatalError(
        f"injected fatal fault at permutation {spec.at_perm}"
    ),
}


@dataclass(frozen=True)
class FaultSpec:
    """One planned fault: raise ``kind`` on the dispatch whose permutation
    range ``[start, start+take)`` covers ``at_perm``, ``times`` successive
    attempts in a row."""

    kind: str
    at_perm: int
    times: int = 1


def parse_plan(spec) -> tuple[FaultSpec, ...]:
    """Parse a plan — a spec string (``"kind@perm[xN]"`` entries joined by
    ``;`` or ``,``), an iterable of :class:`FaultSpec`, or None/"" (empty
    plan). Raises ``ValueError`` on malformed entries so a typo'd CI env
    var fails loudly instead of silently injecting nothing."""
    if not spec:
        return ()
    if not isinstance(spec, str):
        out = tuple(spec)
        for s in out:
            if not isinstance(s, FaultSpec):
                raise ValueError(f"not a FaultSpec: {s!r}")
        return out
    out = []
    for entry in spec.replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, at = entry.split("@", 1)
            times = 1
            if "x" in at:
                at, times_s = at.split("x", 1)
                times = int(times_s)
            fs = FaultSpec(kind.strip(), int(at), times)
        except ValueError as e:
            raise ValueError(
                f"malformed fault-plan entry {entry!r} (want "
                f"'kind@perm' or 'kind@permxN'): {e}"
            ) from None
        if fs.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {fs.kind!r} in plan entry {entry!r}; "
                f"one of {_KINDS}"
            )
        if fs.at_perm < 0 or fs.times < 1:
            raise ValueError(f"bad fault-plan entry {entry!r}")
        out.append(fs)
    return tuple(out)


class FaultInjector:
    """Stateful consumer of a fault plan: :meth:`poll` returns the next
    unconsumed spec covering the dispatch's permutation range (and
    decrements its remaining count), or None. State is per-injector, so a
    runtime shared across an engine rebuild (CPU degradation) never
    re-fires a consumed fault on the resumed dispatches."""

    def __init__(self, specs: tuple[FaultSpec, ...]):
        self.specs = tuple(specs)
        self._remaining = [s.times for s in self.specs]

    def poll(self, start: int, take: int) -> FaultSpec | None:
        for i, s in enumerate(self.specs):
            if self._remaining[i] > 0 and start <= s.at_perm < start + take:
                self._remaining[i] -= 1
                return s
        return None

    @property
    def pending(self) -> int:
        return sum(self._remaining)


# ---------------------------------------------------------------------------
# Retry / abandon / degradation runtime
# ---------------------------------------------------------------------------


def backoff_delay(policy: FaultPolicy, start: int, attempt: int) -> float:
    """Exponential backoff with *deterministic* jitter: the jitter factor
    hashes ``(start, attempt)``, so a rerun of the same faulted run sleeps
    the same schedule (no hidden RNG state, reproducible bench traces)."""
    d = min(
        policy.backoff_max_s,
        policy.backoff_base_s * policy.backoff_factor ** (attempt - 1),
    )
    if policy.backoff_jitter:
        h = int.from_bytes(
            hashlib.blake2b(
                f"{start}:{attempt}".encode(), digest_size=8
            ).digest(),
            "big",
        )
        d *= 1.0 + policy.backoff_jitter * (h / float(2 ** 64) * 2.0 - 1.0)
    return max(0.0, d)


def _block_ready(outs):
    """Force dispatch completion inside the retry scope: JAX dispatch is
    async, so without this a transport failure would surface later at the
    host transfer, outside the per-chunk retry envelope. Tolerant of
    non-JAX leaves (the native backend's numpy outputs)."""
    import jax

    return jax.block_until_ready(outs)


class FaultRuntime:
    """One run's (or one ``module_preservation`` call's) fault-tolerance
    state: the policy, the injector, and the abandon machinery. The null
    loops accept it (or a :class:`FaultPolicy`/True) via ``fault_policy=``
    and wrap every chunk dispatch in :meth:`run_dispatch`."""

    #: worker-thread completion poll period (abandonable dispatches)
    _poll_s = 0.02

    def __init__(self, policy: FaultPolicy,
                 sleep: Callable[[float], None] = time.sleep):
        self.policy = policy
        spec = policy.plan if policy.plan else os.environ.get(PLAN_ENV)
        specs = parse_plan(spec)
        self.injector = FaultInjector(specs) if specs else None
        if (any(s.kind == "hang" for s in specs)
                and policy.hang_timeout_s is None):
            raise ValueError(
                "a 'hang' fault plan needs fault_policy.hang_timeout_s so "
                "the abandoned dispatch can be detected deterministically"
            )
        self._sleep = sleep
        self._abandon = threading.Event()
        self._abandons = 0
        self._wd_wired = False
        self._hang_release = threading.Event()  # never set: injected hang
        # -- elastic mesh state (ISSUE 6), shared across engine rebuilds --
        #: the API layer set this after a mesh-shrink rebuild; check_grow
        #: only ever fires while it is True (growing a never-shrunk mesh
        #: is meaningless, so a stray capacity signal is consumed silently)
        self.mesh_shrunk = False
        #: elastic rebuilds (shrink + grow) performed so far this run —
        #: the API layer caps it at policy.max_mesh_rebuilds
        self.mesh_rebuilds = 0
        self._grow = threading.Event()

    # -- elastic capacity signal (ISSUE 6) ---------------------------------

    def request_grow(self) -> None:
        """Signal that lost device capacity is back. Thread-safe — an
        external capacity monitor may call it at any time; the injected
        ``capacity_restored`` plan kind routes through it too. The loop
        acts at its next chunk boundary (:meth:`check_grow`)."""
        self._grow.set()

    def check_grow(self) -> None:
        """Called by the null loops at each chunk/superchunk boundary
        (committed state only, checkpoint writable): raise
        :class:`CapacityRestoredError` when a grow signal is pending AND
        the mesh was previously shrunk. A signal with nothing to grow
        back to is consumed silently — capacity news on a healthy mesh
        is not actionable."""
        if not self._grow.is_set():
            return
        self._grow.clear()
        if not self.mesh_shrunk:
            return
        raise CapacityRestoredError(
            "device capacity restored; rebuild the mesh at this chunk "
            "boundary and resume from checkpoint"
        )

    # -- watchdog escalation (warn → act) ----------------------------------

    def watchdog_escalation(self, rescue: Callable[[], None] | None):
        """``(action, action_factor)`` for
        :func:`netrep_tpu.utils.telemetry.arm_watchdog`: when a stall
        outlasts ``stall_action_factor`` × the steady chunk time, the
        watchdog THREAD checkpoints completed work (``rescue``) and flags
        the in-flight dispatch for abandonment — the loop thread is
        blocked inside the dispatch and cannot act itself. ``(None,
        None)`` when the policy keeps the watchdog warn-only."""
        if not self.policy.watchdog_action:
            return None, None
        self._wd_wired = True

        def action():
            try:
                if rescue is not None:
                    rescue()
            # netrep: allow(exception-taxonomy) — best-effort emergency checkpoint; the watchdog still abandons the hung dispatch either way
            except Exception:
                logger.warning(
                    "emergency checkpoint from the stall watchdog failed",
                    exc_info=True,
                )
            self._abandon.set()

        return action, self.policy.stall_action_factor

    # -- dispatch wrapper ---------------------------------------------------

    def run_dispatch(
        self,
        call: Callable[[], object],
        *,
        start: int,
        take: int,
        telemetry=None,
        rescue: Callable[[], None] | None = None,
        reset: Callable[[], None] | None = None,
        label: str = "chunk",
    ):
        """Evaluate ``call()`` (blocked until ready) under the recovery
        ladder. ``start``/``take`` name the dispatch's permutation range —
        the retry identity (re-dispatch regenerates the same ``fold_in``
        keys) and the injection coordinate. ``rescue()`` checkpoints
        completed work before an abandonment; ``reset()`` restores loop
        state consumed by a failed attempt (the streaming loop's donated
        tally carry). Raises :class:`DeviceLostError` for the degradation
        ladder, re-raises fatal errors, and passes ``KeyboardInterrupt``
        through untouched (the loops' clean-interrupt contract)."""
        pol = self.policy
        attempt = 0
        while True:
            hang = False
            err = None
            fault = (
                self.injector.poll(start, take)
                if self.injector is not None else None
            )
            if fault is not None and fault.kind == "capacity_restored":
                # not a failure: set the grow signal (acted on by the loop
                # at the NEXT chunk boundary, after this dispatch commits)
                # and keep dispatching; a second spec may cover this range
                if telemetry is not None:
                    telemetry.emit(
                        "fault_injected", kind=fault.kind,
                        at_perm=int(fault.at_perm), start=int(start),
                        take=int(take), label=label,
                    )
                logger.warning(
                    "capacity restored (injected) at permutation %d; the "
                    "mesh grows back at the next %s boundary",
                    fault.at_perm, label,
                )
                self.request_grow()
                fault = (
                    self.injector.poll(start, take)
                    if self.injector is not None else None
                )
            if fault is not None:
                if telemetry is not None:
                    telemetry.emit(
                        "fault_injected", kind=fault.kind,
                        at_perm=int(fault.at_perm), start=int(start),
                        take=int(take), label=label,
                    )
                logger.warning(
                    "fault injected: %s at permutation %d (%s dispatch "
                    "at %d)", fault.kind, fault.at_perm, label, start,
                )
                if fault.kind == "interrupt":
                    raise KeyboardInterrupt
                if fault.kind == "sigkill":
                    # the real thing, for the chaos --serve subprocess
                    # drill: the process dies HERE, mid-pack, with no
                    # cleanup — recovery must come from the journal and
                    # the periodic checkpoints alone
                    import signal as _signal

                    os.kill(os.getpid(), _signal.SIGKILL)
                if fault.kind == "crash":
                    # in-process SIGKILL stand-in (BaseException): the
                    # loops' failure-save hooks run, nothing else does
                    raise SimulatedCrash(
                        f"injected crash at permutation {fault.at_perm}"
                    )
                if fault.kind == "hang":
                    hang = True
                else:
                    err = _RAISERS[fault.kind](fault)
            try:
                if err is not None:
                    raise err
                target = (
                    (lambda: self._hang_release.wait()) if hang
                    else (lambda: _block_ready(call()))
                )
                if hang or pol.hang_timeout_s is not None or self._wd_wired:
                    return self._call_abandonable(
                        target, telemetry=telemetry, start=start, take=take,
                        rescue=rescue, label=label,
                    )
                return target()
            except KeyboardInterrupt:
                raise
            except Exception as e:
                kind = classify_error(e)
                if kind == "device_lost":
                    if telemetry is not None:
                        telemetry.emit(
                            "device_lost", start=int(start), take=int(take),
                            error=type(e).__name__, label=label,
                        )
                    logger.warning(
                        "device-loss-class failure during %s dispatch at "
                        "permutation %d: %s: %s", label, start,
                        type(e).__name__, e,
                    )
                    if not pol.degrade_to_cpu:
                        raise
                    raise DeviceLostError(
                        f"device lost during {label} dispatch at "
                        f"permutation {start}; completed work is "
                        "checkpointed — shrink onto the survivors (or "
                        "degrade to CPU) and resume"
                    ) from e
                if kind != "transient":
                    raise
                if attempt >= pol.max_retries:
                    # retries exhausted: a backend that fails every
                    # re-dispatch is as dead as a lost device — hand the
                    # run to the degradation ladder instead of crashing
                    # with the last transient error
                    if not pol.degrade_to_cpu:
                        raise
                    if telemetry is not None:
                        telemetry.emit(
                            "device_lost", start=int(start), take=int(take),
                            error=type(e).__name__, label=label,
                            retries=attempt,
                        )
                    logger.warning(
                        "transient retries exhausted (%d) for %s dispatch "
                        "at permutation %d; backend presumed dead", attempt,
                        label, start,
                    )
                    raise DeviceLostError(
                        f"transient retries exhausted ({attempt}) for "
                        f"{label} dispatch at permutation {start}; "
                        "completed work is checkpointed — degrade to CPU "
                        "and resume",
                        reason="retries_exhausted",
                    ) from e
                attempt += 1
                delay = backoff_delay(pol, start, attempt)
                if telemetry is not None:
                    telemetry.emit(
                        "retry_attempt", start=int(start), take=int(take),
                        attempt=attempt, max_retries=pol.max_retries,
                        delay_s=float(delay), error=type(e).__name__,
                        label=label,
                    )
                logger.warning(
                    "transient %s during %s dispatch at permutation %d; "
                    "retry %d/%d in %.2gs", type(e).__name__, label, start,
                    attempt, pol.max_retries, delay,
                )
                if delay > 0:
                    self._sleep(delay)
                if reset is not None:
                    reset()

    def _call_abandonable(self, target, *, telemetry, start, take, rescue,
                          label):
        """Run ``target`` on a daemon worker thread so a dispatch hung in
        a no-deadline gRPC call can be walked away from: on
        ``hang_timeout_s`` elapsing or the watchdog's abandon flag, emit
        ``chunk_abandoned``, checkpoint completed work, and raise
        :class:`DispatchAbandonedError` (transient → the retry ladder
        re-dispatches). The abandoned thread is leaked deliberately — it
        is blocked in native code and cannot be interrupted; a later
        completion is discarded."""
        box: dict = {}
        done = threading.Event()

        def worker():
            try:
                box["out"] = target()
            # netrep: allow(exception-taxonomy) — not swallowed: captured verbatim (BaseException included) and re-raised on the loop thread by the done.wait consumer
            except BaseException as e:  # delivered to the loop thread below
                box["err"] = e
            finally:
                done.set()

        self._abandon.clear()
        t0 = time.monotonic()
        threading.Thread(
            target=worker, name="netrep-ft-dispatch", daemon=True
        ).start()
        deadline = self.policy.hang_timeout_s
        while not done.wait(self._poll_s):
            waited = time.monotonic() - t0
            timed_out = deadline is not None and waited > deadline
            if not (self._abandon.is_set() or timed_out):
                continue
            by = "watchdog" if self._abandon.is_set() else "timeout"
            self._abandons += 1
            if telemetry is not None:
                telemetry.emit(
                    "chunk_abandoned", start=int(start), take=int(take),
                    waited_s=float(waited), by=by,
                    abandons=self._abandons, label=label,
                )
            logger.warning(
                "abandoning hung %s dispatch at permutation %d after "
                "%.2gs (%s); completed work is checkpointed and the "
                "chunk will be re-dispatched", label, start, waited, by,
            )
            if by == "timeout" and rescue is not None:
                # the watchdog path already checkpointed from its thread
                try:
                    rescue()
                # netrep: allow(exception-taxonomy) — best-effort emergency checkpoint; the abandon raises DispatchAbandonedError regardless
                except Exception:
                    logger.warning(
                        "emergency checkpoint on abandon failed",
                        exc_info=True,
                    )
            if self._abandons > self.policy.max_abandons:
                # repeated hangs = the backend is gone, not slow: hand the
                # run to the degradation ladder instead of spinning
                msg = (
                    f"{label} dispatch abandoned {self._abandons} times "
                    f"(max_abandons={self.policy.max_abandons}); backend "
                    "presumed dead"
                )
                if self.policy.degrade_to_cpu:
                    raise DeviceLostError(msg, reason="abandons_exhausted")
                raise RuntimeError(msg)
            raise DispatchAbandonedError(
                f"{label} dispatch at permutation {start} abandoned "
                f"after {waited:.2g}s ({by})"
            )
        if "err" in box:
            raise box["err"]
        return box["out"]


def resolve_runtime(arg) -> FaultRuntime | None:
    """``fault_policy=`` argument → runtime: None/False = off (unless
    ``NETREP_FAULT_PLAN`` is set, which activates a default policy so CI
    and bench can inject faults into any run); True = default policy; a
    :class:`FaultPolicy` builds a fresh runtime; an existing
    :class:`FaultRuntime` passes through — how ``module_preservation``
    shares one injector across a mid-run engine rebuild."""
    if isinstance(arg, FaultRuntime):
        return arg
    if arg is None or arg is False:
        if not os.environ.get(PLAN_ENV):
            return None
        return FaultRuntime(FaultPolicy())
    if arg is True:
        return FaultRuntime(FaultPolicy())
    if isinstance(arg, FaultPolicy):
        return FaultRuntime(arg)
    raise TypeError(
        "fault_policy must be None/False, True, a FaultPolicy, or a "
        f"FaultRuntime; got {type(arg).__name__}"
    )
