"""Tracing / profiling hooks (SURVEY.md §5 "Tracing / profiling").

The reference has nothing beyond a progress bar and ``verbose=`` messages —
users profile with ``system.time``/``Rprof`` (SURVEY.md §5). The rebuild
exposes the TPU-native equivalents:

- ``profile=`` on :func:`netrep_tpu.module_preservation` captures a
  ``jax.profiler`` trace (TensorBoard/Perfetto ``.xplane.pb``) of the
  permutation run plus per-pair wall-clock and per-chunk timings, attached
  to each result as ``result.profile``.
- :func:`summarize_trace` aggregates the captured device-op durations into a
  printable table without needing TensorBoard — the same parsing the round-2
  hot-loop work used to find the gather bottleneck
  (``benchmarks/profile_chunk.py``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob
import logging
import os
import re
import time
from typing import Callable

logger = logging.getLogger("netrep_tpu")


@dataclasses.dataclass
class NullProfile:
    """Dispatch/transfer accounting for one null run — the observability
    counterpart of the superchunk executor's claims (ISSUE 2): the chunked
    loops count every jitted program they launch and every byte they pull
    to the host, so "K× fewer dispatches, O(m·7) transferred per
    superchunk" is a measured row (``bench.py --config superchunk``), not
    an assertion. ``superchunks`` records one entry per streaming
    superchunk (dispatches issued for it + host bytes pulled), letting a
    regression in either show up per-dispatch rather than only in totals.
    """

    #: jitted program launches issued (chunk/superchunk programs + the
    #: per-chunk key derivation — each is one host→device round-trip that
    #: costs ~1 s of dispatch latency on tunneled backends)
    dispatches: int = 0
    #: bytes moved device→host (null chunks or streamed tallies)
    host_bytes: int = 0
    #: per-superchunk records: {"dispatches", "host_bytes", "perms"}
    superchunks: list = dataclasses.field(default_factory=list)
    #: modeled FLOPs executed (ISSUE 18: fed by the null loops with the
    #: SAME integers their chunk/superchunk events carry, so per-family
    #: span sums reconcile with these totals exactly; telemetry-on runs
    #: only — the cost model is never resolved on the disabled path)
    flops: int = 0
    #: modeled HBM bytes touched (same exact-reconciliation contract)
    cost_bytes: int = 0
    #: per-program-family rollup: {family: {"flops", "bytes_hbm", "perms"}}
    families: dict = dataclasses.field(default_factory=dict)

    def record_dispatch(self, n: int = 1) -> None:
        self.dispatches += int(n)

    def record_transfer(self, nbytes: int) -> None:
        self.host_bytes += int(nbytes)

    def record_superchunk(self, dispatches: int, host_bytes: int,
                          perms: int) -> None:
        self.superchunks.append({
            "dispatches": int(dispatches),
            "host_bytes": int(host_bytes),
            "perms": int(perms),
        })

    def record_cost(self, flops: int, bytes_hbm: int, family: str,
                    perms: int) -> None:
        """Fold one chunk/superchunk's modeled cost (the integers its
        telemetry event carries) into the run totals and the per-family
        rollup (:mod:`netrep_tpu.utils.costmodel`)."""
        self.flops += int(flops)
        self.cost_bytes += int(bytes_hbm)
        fam = self.families.setdefault(
            str(family), {"flops": 0, "bytes_hbm": 0, "perms": 0})
        fam["flops"] += int(flops)
        fam["bytes_hbm"] += int(bytes_hbm)
        fam["perms"] += int(perms)

    def as_dict(self) -> dict:
        out = {
            "dispatches": self.dispatches,
            "host_bytes": self.host_bytes,
            "superchunks": list(self.superchunks),
        }
        if self.families:
            # additive (ISSUE 18): cost keys appear only on telemetry-on
            # runs that resolved a model, so the PR 2 payload shape is
            # unchanged everywhere else
            out["flops"] = self.flops
            out["bytes_hbm"] = self.cost_bytes
            out["families"] = {k: dict(v) for k, v in self.families.items()}
        return out


@contextlib.contextmanager
def device_trace(trace_dir: str | None):
    """Best-effort ``jax.profiler.trace`` context: profiling must never turn
    a working run into a failing one (e.g. when the backend's profiler
    plugin is unavailable), so failures degrade to a warning."""
    if trace_dir is None:
        yield
        return
    os.makedirs(trace_dir, exist_ok=True)
    import jax

    try:
        with jax.profiler.trace(trace_dir):
            yield
    # netrep: allow(exception-taxonomy) — profiling is observability: a backend that cannot trace must not fail the run (timings still collect)
    except Exception as e:  # pragma: no cover - backend-dependent
        logger.warning("profiler trace failed (%s: %s); timings are still "
                       "collected", type(e).__name__, e)
        yield


class PairTimer:
    """Collects per-pair wall-clock and per-chunk durations.

    The chunk timer piggybacks on the engine's ``progress`` callback — the
    loop calls it once per completed chunk, so inter-call deltas are chunk
    wall times (including the overlapped host transfer of the
    double-buffered loop).
    """

    def __init__(self, trace_dir: str | None):
        self.trace_dir = trace_dir
        self.chunk_s: list[float] = []
        self.observed_s: float | None = None
        self.null_s: float | None = None
        self._t0: float | None = None
        self._null_start: float | None = None

    def time_observed(self, fn: Callable):
        t0 = time.perf_counter()
        out = fn()
        self.observed_s = time.perf_counter() - t0
        return out

    def wrap_progress(self, progress: Callable | None) -> Callable:
        self._t0 = self._null_start = time.perf_counter()

        def cb(done, total):
            now = time.perf_counter()
            self.chunk_s.append(now - self._t0)
            self._t0 = now
            if progress is not None:
                progress(done, total)

        return cb

    def finish_null(self, completed: int) -> dict:
        # wrap_progress may never have run (zero-chunk or failed null
        # path): report null_s as unmeasured rather than crashing on the
        # unset start mark
        if self._null_start is not None:
            self.null_s = time.perf_counter() - self._null_start
        return self.as_dict(completed)

    def as_dict(self, completed: int) -> dict:
        """The ``result.profile`` payload (SURVEY.md §5 deliverable)."""
        chunks = self.chunk_s
        return {
            "trace_dir": self.trace_dir,
            "observed_s": self.observed_s,
            "null_s": self.null_s,
            "completed": completed,
            "perms_per_sec": (
                completed / self.null_s if self.null_s else None
            ),
            "chunk_ms": [s * 1e3 for s in chunks],
            # the first chunk's time includes jit compilation; later chunks
            # hit the executable cache (SURVEY.md §7: jit once per bucket)
            "compile_chunk_ms": chunks[0] * 1e3 if chunks else None,
            "steady_chunk_ms": (
                sorted(chunks[1:])[len(chunks[1:]) // 2] * 1e3
                if len(chunks) > 1 else None
            ),
        }


def make_memory_probe():
    """Per-chunk device-memory gauge factory (ISSUE 5 compile & memory
    accounting): returns a zero-arg callable yielding telemetry fields —
    ``mem_bytes_in_use``/``mem_peak_bytes`` from ``device.memory_stats()``
    where the backend implements it, else ``mem_live_buffer_bytes`` summed
    over ``jax.live_arrays()`` — or None when neither works. Guarded like
    the backend probes: memory accounting must never turn a working run
    into a failing one, and the probe decision is made ONCE per run so the
    per-chunk cost is one dict build."""
    try:
        import jax

        dev = jax.devices()[0]
    # netrep: allow(exception-taxonomy) — memory-telemetry probe: no resolvable device just disables memory columns
    except Exception:
        return None

    def stats_probe():
        out = {}
        try:
            ms = dev.memory_stats()
        # netrep: allow(exception-taxonomy) — memory_stats() is optional per backend; absent stats just skip the columns
        except Exception:
            return out
        if not isinstance(ms, dict):
            return out
        if "bytes_in_use" in ms:
            out["mem_bytes_in_use"] = int(ms["bytes_in_use"])
        if "peak_bytes_in_use" in ms:
            out["mem_peak_bytes"] = int(ms["peak_bytes_in_use"])
        return out

    def live_probe():
        try:
            import jax

            return {
                "mem_live_buffer_bytes": int(sum(
                    int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()
                ))
            }
        # netrep: allow(exception-taxonomy) — live-buffer probe fallback: a failing enumeration only drops the telemetry field
        except Exception:
            return {}

    if stats_probe():
        return stats_probe
    if live_probe():
        return live_probe
    return None


def resolve_profile_dir(profile) -> str | None:
    """``profile=`` argument → trace directory (None = profiling off)."""
    if profile is None or profile is False:
        return None
    if profile is True:
        return os.path.join(os.getcwd(), "netrep_profile")
    return str(profile)


#: op-name patterns that mark device↔host (or cross-device) data movement
#: in a trace — the "transfer" side of the scan-body/transfer split. XLA
#: names differ per backend/version, so matching is deliberately broad;
#: everything matching neither bucket lands in "other".
_TRANSFER_OPS = re.compile(
    r"copy|transfer|infeed|outfeed|send|recv|h2d|d2h", re.IGNORECASE
)
#: op-name patterns of the streaming executor's fused dispatch: lax.scan
#: lowers to a while loop, so its body ops carry while/scan context names.
_SCAN_OPS = re.compile(r"scan|while|body", re.IGNORECASE)


def trace_time_split(trace_dir: str) -> dict:
    """Classify a captured trace's device-op time into scan-body vs
    transfer vs other — re-measuring the round-2 profile's "serial
    device→host transfer gap is ~25% of wall-clock" claim after the
    superchunk executor amortizes it: a streaming run's split should show
    the transfer share collapsing while scan-body time dominates.

    Returns ``{"scan_body_ms", "transfer_ms", "other_ms", "total_ms",
    "transfer_frac"}`` summed over accelerator planes (all zeros on
    host-only traces). Name-pattern classification is heuristic — use it
    for before/after deltas on one backend, not cross-backend absolutes.
    """
    split = {"scan_body_ms": 0.0, "transfer_ms": 0.0, "other_ms": 0.0}
    for name, ns in _device_op_durations(trace_dir).items():
        if _TRANSFER_OPS.search(name):
            split["transfer_ms"] += ns / 1e6
        elif _SCAN_OPS.search(name):
            split["scan_body_ms"] += ns / 1e6
        else:
            split["other_ms"] += ns / 1e6
    total = sum(split.values())
    split["total_ms"] = total
    split["transfer_frac"] = (split["transfer_ms"] / total) if total else 0.0
    return split


#: one-shot flag for the xplane-parse downgrade below (the benign case
#: repeats for every trace in a session; genuine information is one line)
_XPLANE_UNSUPPORTED_WARNED = False


def _device_op_durations(trace_dir: str) -> dict[str, float]:
    """Per-op total duration (ns) over accelerator planes of the newest
    xplane in ``trace_dir`` — the shared parse behind
    :func:`summarize_trace` and :func:`trace_time_split`.

    The xplane reader API moves between jax releases
    (``jax.profiler.ProfileData`` is absent in some installed versions,
    and its attribute layout has shifted) — a missing/incompatible reader
    degrades to an empty-but-valid op table with ONE warning instead of
    raising, so ``profile=`` keeps collecting wall-clock timings on every
    jax this package imports under."""
    global _XPLANE_UNSUPPORTED_WARNED
    import jax

    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        return {}
    with open(paths[-1], "rb") as f:
        raw = f.read()
    per_op: dict[str, float] = {}
    try:
        pd_ = jax.profiler.ProfileData.from_serialized_xspace(raw)
        for plane in pd_.planes:
            if ("tpu" not in plane.name.lower()
                    and "gpu" not in plane.name.lower()):
                continue
            for line in plane.lines:
                for ev in line.events:
                    base = re.sub(r"[.\d]+$", "", ev.name)
                    per_op[base] = per_op.get(base, 0.0) + ev.duration_ns
    except (AttributeError, TypeError, ValueError) as e:
        if not _XPLANE_UNSUPPORTED_WARNED:
            _XPLANE_UNSUPPORTED_WARNED = True
            logger.warning(
                "installed jax cannot parse xplane traces (%s: %s); "
                "per-op device tables will be empty — wall-clock timings "
                "are unaffected", type(e).__name__, e,
            )
        return {}
    return per_op


def summarize_trace(trace_dir: str, top: int = 20, split: bool = False):
    """Aggregate a captured trace's device-op durations.

    Returns ``[(op_name, total_ms, percent), ...]`` sorted by time, summed
    over accelerator planes (empty on hosts whose trace has no device
    plane). Lets users see the hot ops without TensorBoard. With
    ``split=True`` returns ``(rows, split_dict)`` where ``split_dict`` is
    :func:`trace_time_split`'s scan-body/transfer/other classification.
    """
    per_op = _device_op_durations(trace_dir)
    total = sum(per_op.values())
    ranked = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    rows = [
        (name, ns / 1e6, (ns / total * 100.0) if total else 0.0)
        for name, ns in ranked
    ]
    if split:
        return rows, trace_time_split(trace_dir)
    return rows
