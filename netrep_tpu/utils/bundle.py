"""One-command diagnostic bundles (ISSUE 20).

:func:`collect` writes a self-contained ``netrep-bundle-<reason>/``
directory — the artifact a post-incident session (or a human on the
other side of a dead tunnel) reads INSTEAD of the process that no longer
exists:

- ``flight_ring.jsonl`` — the black box: the flight recorder's ring of
  recent events (:mod:`netrep_tpu.utils.flightrec`);
- ``manifest.json`` — reason, wall time, host, pid, ring stats;
- ``env.json`` — filtered environment (``NETREP_*`` / ``JAX_*`` /
  ``XLA_*`` / ``TPU*`` keys only — never the whole environ), python /
  jax / jaxlib versions, and the device inventory IF a backend is
  already resolved (the probe is never triggered: collecting forensics
  about a dead tunnel must not hang on that same tunnel);
- ``autotune.json`` / ``aot.json`` — metadata snapshots of the autotune
  cache and AOT store (paths, entry names, sizes — no payloads);
- ``perf_ledger_tail.jsonl`` — the newest perf-ledger entries;
- ``journal_tail.jsonl`` — the newest serve-journal records, content-
  REDACTED: scalar metadata survives, every array/large payload is
  replaced by its digest — a bundle must never carry raw tenant
  matrices off the box (pinned by tests);
- ``stacks.txt`` — faulthandler dump of every thread's stack;
- ``roofline.json`` — the process's last roofline note.

The write is atomic at the directory level: everything is staged into a
``.tmp-<pid>`` sibling and ``os.rename``\\ d into place, so a half-
written bundle is never mistaken for a real one. :func:`render_report`
turns a bundle back into a one-screen triage report (detector verdicts,
timeline, time split) for ``python -m netrep_tpu bundle <dir>``.
"""

from __future__ import annotations

import faulthandler
import hashlib
import json
import os
import platform
import sys
import time

from . import flightrec
from . import telemetry as tm

#: bundle layout version, stamped in the manifest
FORMAT_VERSION = 1

#: tail sizes — enough context to triage, bounded so a bundle stays small
JOURNAL_TAIL = 64
LEDGER_TAIL = 50

#: env keys worth shipping (prefix match); everything else stays on the box
_ENV_PREFIXES = ("NETREP_", "JAX_", "XLA_", "TPU", "LIBTPU")

#: redaction thresholds: any sequence, any string/mapping beyond these
#: bounds, is digest-only in the journal tail
_REDACT_STR = 256
_REDACT_KEYS = 32


def _best_effort(fn):
    """Run one bundle-section builder; a broken source costs exactly that
    section (an ``error`` stub), never the bundle."""
    try:
        return fn()
    # netrep: allow(exception-taxonomy) — bundle sections are best-effort forensics; a broken source must cost one section, not the whole bundle
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _digest(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()[:16]


def redact(value):
    """Content-redact one journal value: scalars and small mappings pass
    through, every sequence / oversized string / oversized mapping is
    replaced by ``{"redacted", "sha256", "bytes"}`` — the digest still
    lets two bundles be compared for identical payloads without either
    ever containing one."""
    if isinstance(value, dict):
        if len(value) > _REDACT_KEYS:
            blob = json.dumps(value, sort_keys=True, default=str).encode()
            return {"redacted": "mapping", "keys": len(value),
                    "sha256": _digest(blob), "bytes": len(blob)}
        return {k: redact(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        blob = json.dumps(value, default=str).encode()
        return {"redacted": "sequence", "items": len(value),
                "sha256": _digest(blob), "bytes": len(blob)}
    if isinstance(value, str) and len(value) > _REDACT_STR:
        blob = value.encode("utf-8", errors="replace")
        return {"redacted": "text", "chars": len(value),
                "sha256": _digest(blob), "bytes": len(blob)}
    return value


def _jax_info() -> dict:
    """jax/jaxlib versions + device inventory — WITHOUT ever triggering
    backend resolution (the documented dead-tunnel hang). Devices are
    listed only when some earlier code already resolved a backend."""
    if "jax" not in sys.modules:
        return {"loaded": False}
    import jax

    info: dict = {"loaded": True, "jax": getattr(jax, "__version__", "?")}
    jaxlib = sys.modules.get("jaxlib")
    if jaxlib is not None:
        info["jaxlib"] = getattr(jaxlib, "__version__", None)
    xb = sys.modules.get("jax._src.xla_bridge")
    if getattr(xb, "_backends", None):
        info["devices"] = [str(d) for d in jax.devices()]
        info["backend"] = jax.default_backend()
    else:
        info["devices"] = "unresolved (never probed from a bundle)"
    return info


def _env_snapshot() -> dict:
    return {
        "python": sys.version,
        "platform": platform.platform(),
        "env": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith(_ENV_PREFIXES)
        },
        "jax": _best_effort(_jax_info),
    }


def _autotune_snapshot() -> dict:
    from . import autotune

    path = autotune.default_path()
    out: dict = {"path": path, "exists": os.path.exists(path)}
    if out["exists"]:
        out["bytes"] = os.path.getsize(path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("entries", data)
        if isinstance(entries, dict):
            out["n_keys"] = len(entries)
            out["keys"] = sorted(entries)[:50]
    return out


def _aot_snapshot() -> dict:
    from . import aot

    d = aot.default_dir()
    out: dict = {"dir": d, "entries": []}
    if os.path.isdir(d):
        for name in sorted(os.listdir(d))[:200]:
            p = os.path.join(d, name)
            try:
                out["entries"].append(
                    {"name": name, "bytes": os.path.getsize(p)}
                )
            except OSError:
                continue
    return out


def _tail_lines(path: str, n: int) -> list[str]:
    with open(path, encoding="utf-8", errors="replace") as f:
        return [ln.rstrip("\n") for ln in f][-n:]


def _write_json(path: str, obj) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True, default=str)
        f.write("\n")


def _slug(reason: str) -> str:
    return "".join(
        c if c.isalnum() or c in "-_" else "-" for c in str(reason)
    ) or "manual"


def collect(dest: str | None = None, reason: str = "manual",
            telemetry=None, journal: str | None = None) -> str:
    """Collect one diagnostic bundle; returns the final directory path.

    ``dest`` is the wanted directory (``netrep-bundle-<reason>`` in the
    CWD when None); an existing directory gets a ``-2``/``-3`` suffix
    instead of being overwritten. ``journal`` names the serve journal to
    tail (redacted) when the caller has one."""
    reason = _slug(reason)
    if dest is None:
        dest = os.path.join(os.getcwd(), f"netrep-bundle-{reason}")
    dest = os.path.abspath(dest)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    stage = f"{dest}.tmp-{os.getpid()}"
    if os.path.isdir(stage):
        import shutil

        shutil.rmtree(stage)
    os.makedirs(stage)

    tel = tm.resolve(telemetry)
    rec = flightrec.recorder()
    # the dump mark goes out FIRST so the drained ring records its own
    # dump — a bundle's ring is self-describing about why it exists
    if tel is not None:
        tel.emit("flightrec_dump", reason=reason,
                 entries=(rec.stats()["entries"] if rec is not None else 0))
    n_ring = 0
    if rec is not None:
        n_ring = rec.dump_jsonl(os.path.join(stage, "flight_ring.jsonl"))

    _write_json(os.path.join(stage, "manifest.json"), {
        "format": FORMAT_VERSION,
        "reason": reason,
        "t": time.time(),
        "host": platform.node(),
        "pid": os.getpid(),
        "argv": sys.argv,
        "ring": (rec.stats() if rec is not None
                 else {"disabled": True, "entries": 0}),
    })
    _write_json(os.path.join(stage, "env.json"),
                _best_effort(_env_snapshot))
    _write_json(os.path.join(stage, "autotune.json"),
                _best_effort(_autotune_snapshot))
    _write_json(os.path.join(stage, "aot.json"),
                _best_effort(_aot_snapshot))

    def _ledger_tail():
        from . import perfledger

        path = perfledger.default_path()
        lines = _tail_lines(path, LEDGER_TAIL) if os.path.exists(path) else []
        with open(os.path.join(stage, "perf_ledger_tail.jsonl"), "w",
                  encoding="utf-8") as f:
            for ln in lines:
                f.write(ln + "\n")
        return {"path": path, "entries": len(lines)}

    _best_effort(_ledger_tail)

    def _journal_tail():
        out = os.path.join(stage, "journal_tail.jsonl")
        lines = (_tail_lines(journal, JOURNAL_TAIL)
                 if journal and os.path.exists(journal) else [])
        with open(out, "w", encoding="utf-8") as f:
            for ln in lines:
                try:
                    rec_ = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                f.write(json.dumps(redact(rec_), default=str) + "\n")
        return {"path": journal, "entries": len(lines)}

    _best_effort(_journal_tail)

    def _stacks():
        with open(os.path.join(stage, "stacks.txt"), "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)

    _best_effort(_stacks)

    def _roofline():
        from . import costmodel

        note = costmodel.last_run_note(consume=False)
        _write_json(os.path.join(stage, "roofline.json"),
                    note if note is not None else {"note": None})

    _best_effort(_roofline)

    final = dest
    n = 1
    while os.path.exists(final):
        n += 1
        final = f"{dest}-{n}"
    os.rename(stage, final)
    if tel is not None:
        tel.emit("bundle_written", reason=reason, path=final,
                 ring_entries=n_ring)
    return final


# ---------------------------------------------------------------------------
# triage report (`python -m netrep_tpu bundle <dir>`)
# ---------------------------------------------------------------------------


def _load_json(path: str):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def render_report(path: str) -> str:
    """One-screen human triage report of a collected bundle: header,
    detector verdicts, recovery/forensic timeline, and the per-phase time
    split folded from the flight ring."""
    path = os.path.abspath(path)
    manifest_path = os.path.join(path, "manifest.json")
    if not os.path.isfile(manifest_path):
        raise ValueError(f"{path!r} is not a diagnostic bundle "
                         "(no manifest.json)")
    man = _load_json(manifest_path)
    out = [f"netrep diagnostic bundle: {os.path.basename(path)}"]
    when = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                         time.gmtime(man.get("t", 0)))
    out.append(f"  reason={man.get('reason')} written={when} "
               f"host={man.get('host')} pid={man.get('pid')}")
    ring_stats = man.get("ring") or {}
    out.append(f"  ring: {ring_stats.get('entries', 0)} entries "
               f"({ring_stats.get('n_seen', 0)} seen, "
               f"{ring_stats.get('n_evicted', 0)} evicted)")
    env_path = os.path.join(path, "env.json")
    if os.path.isfile(env_path):
        env = _load_json(env_path)
        jx = env.get("jax") or {}
        jax_v = jx.get(
            "jax", "not-loaded" if jx.get("loaded") is False else "?"
        )
        out.append(f"  python={str(env.get('python', '?')).split()[0]} "
                   f"jax={jax_v} devices={jx.get('devices', '-')}")

    ring_file = os.path.join(path, "flight_ring.jsonl")
    ring = (list(tm.read_events(ring_file))
            if os.path.isfile(ring_file) else [])

    out.append("")
    out.append("detector verdicts:")
    anomalies = [e for e in ring if e["ev"] == "anomaly_detected"]
    if not anomalies:
        out.append("  (no detector fired inside the recorded window)")
    else:
        by_det: dict[str, list[dict]] = {}
        for e in anomalies:
            by_det.setdefault(
                str(e["data"].get("detector", "-")), []
            ).append(e)
        for det in sorted(by_det):
            evs = by_det[det]
            last = evs[-1]["data"]
            detail = " ".join(
                f"{k}={v}" for k, v in last.items()
                if k not in ("detector", "span", "parent")
            )
            out.append(f"  {det:<20} x{len(evs)}  last: {detail}")

    out.append("")
    out.append("timeline (recovery / fleet / forensic events):")
    t0 = ring[0]["t"] if ring else 0.0
    shown = 0
    for e in ring:
        if (e["ev"] not in tm.RECOVERY_EVENTS
                and e["ev"] not in tm.FLEET_EVENTS
                and e["ev"] not in tm.FORENSIC_EVENTS):
            continue
        d = dict(e["data"])
        label = ""
        if e["ev"] in tm.FORENSIC_EVENTS:
            label = f" [detector={d.pop('detector', '-')}]"
        data = " ".join(f"{k}={v}" for k, v in d.items()
                        if k not in ("span", "parent"))
        out.append(f"  +{e['t'] - t0:9.2f}s  {e['ev']:<24}{label} {data}")
        shown += 1
    if not shown:
        out.append("  (none in the recorded window)")

    out.append("")
    out.append("time split (timed phases in the ring):")
    split: dict[str, list[float]] = {}
    for e in ring:
        s = e["data"].get("s")
        if isinstance(s, (int, float)) and not isinstance(s, bool):
            agg = split.setdefault(e["ev"], [0, 0.0])
            agg[0] += 1
            agg[1] += float(s)
    if not split:
        out.append("  (no timed events in the ring)")
    else:
        total = sum(v[1] for v in split.values()) or 1.0
        for ev in sorted(split, key=lambda k: -split[k][1]):
            n, s = split[ev]
            out.append(f"  {ev:<24} {s:8.3f}s over {n:4d} event(s) "
                       f"({100 * s / total:3.0f}%)")
    return "\n".join(out)
