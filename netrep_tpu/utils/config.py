"""TPU engine configuration.

The reference's knobs are plain function arguments (SURVEY.md §5 "Config /
flag system" — args-only philosophy, kept for the public API); the handful of
TPU-specific tuning parameters live in this small dataclass instead of
growing the user-facing signatures.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Tuning knobs for the permutation engine (SURVEY.md §5).

    Attributes
    ----------
    chunk_size : permutations evaluated per device dispatch. Chunking bounds
        device memory, lets Python regain control between dispatches
        (KeyboardInterrupt → clean partial results, SURVEY.md §5 "failure
        detection"), and is the save/resume granularity.
    summary_method : 'power' (masked power iteration — MXU-friendly, the
        default) or 'eigh' (exact; used by parity tests).
    power_iters : fixed power-iteration count (static under jit).
    bucket_rounding : module bucket capacities are rounded up to the next
        power of two and at least this value — fewer distinct compiled
        programs (SURVEY.md §7: jit once per module-size bucket).
    dtype : matrix element dtype on device ('float32' or 'bfloat16' for the
        gather-bound large-n path; statistics always accumulate in f32).
    mesh_axis : name of the permutation data-parallel mesh axis.
    matrix_sharding : 'replicated' (matrices fit in one HBM; permutation
        axis only) or 'row' (n×n matrices row-sharded over the mesh's row
        axis with psum-assembled module gathers — SURVEY.md §5 long-context
        analogue, Config D scale).
    gather_mode : 'direct' (2D advanced-index gather — what XLA:CPU runs
        fastest), 'mxu' (sorted row gather + one-hot column select + unsort
        matmuls, :func:`netrep_tpu.ops.stats.gather_and_stats_mxu` — ~20×
        faster on TPU where per-element gathers crawl), or 'auto' (mxu on
        TPU, direct elsewhere). Both modes produce identical statistics.
    perm_batch : permutations evaluated concurrently inside one chunk
        dispatch on the mxu path (``lax.map`` batch size). Bounds the
        (batch, Σ K_b·cap_b, n) row-gather working set in HBM; the chunk
        itself stays one dispatch, so host round-trips are unaffected.
    """

    chunk_size: int = 128
    summary_method: str = "power"
    power_iters: int = 60
    bucket_rounding: int = 8
    dtype: str = "float32"
    mesh_axis: str = "perm"
    matrix_sharding: str = "replicated"
    gather_mode: str = "auto"
    perm_batch: int = 2

    def resolved_gather_mode(self, platform: str) -> str:
        if self.gather_mode == "auto":
            # accelerators (tpu / the axon tunnel backend) get the
            # sorted-rows+MXU path; XLA:CPU's native gather is already fast
            return "direct" if platform == "cpu" else "mxu"
        if self.gather_mode not in ("direct", "mxu"):
            raise ValueError(
                f"gather_mode must be 'auto', 'direct', or 'mxu', got "
                f"{self.gather_mode!r}"
            )
        return self.gather_mode

    def rounded_cap(self, size: int) -> int:
        cap = self.bucket_rounding
        while cap < size:
            cap *= 2
        return cap
