"""TPU engine configuration.

The reference's knobs are plain function arguments (SURVEY.md §5 "Config /
flag system" — args-only philosophy, kept for the public API); the handful of
TPU-specific tuning parameters live in this small dataclass instead of
growing the user-facing signatures.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class FaultPolicy:
    """Recovery knobs for fault-tolerant null execution (ISSUE 4;
    :mod:`netrep_tpu.utils.faults`), surfaced as
    ``module_preservation(fault_policy=...)``. ``None`` (the default)
    keeps every null loop bit-identical to previous releases.

    Attributes
    ----------
    max_retries : re-dispatch attempts per chunk for *transient* failures
        (gRPC deadline, dropped tunnel — see
        :func:`netrep_tpu.utils.faults.classify_error`). Retries are exact
        by construction: chunk *i* regenerates identical ``fold_in`` keys.
    backoff_base_s / backoff_factor / backoff_max_s : exponential backoff
        between attempts — ``base * factor**(attempt-1)`` capped at
        ``max``.
    backoff_jitter : +- fraction of the delay, derived deterministically
        from ``(chunk start, attempt)`` — reproducible schedules, no
        hidden RNG state.
    degrade_to_cpu : a *device-loss*-class failure (or repeated hang
        abandonment) saves an emergency checkpoint and hands the run to
        the elastic ladder: the mesh is rebuilt from the surviving
        devices when any survive (ISSUE 6), and only a total loss forces
        the CPU platform; either way the engine is rebuilt and resumes
        bit-identically mid-run. False propagates the error after the
        checkpoint instead.
    max_mesh_rebuilds : elastic mesh rebuilds (shrink + grow-back)
        tolerated per run; once spent, a further device loss skips the
        elastic rungs and takes the final CPU rung directly — a mesh
        that keeps losing devices is a sick pod, not a recoverable one.
    async_checkpoint : write checkpoints from a background thread
        (bounded latest-wins queue, still atomic renames —
        :class:`netrep_tpu.utils.checkpoint.AsyncCheckpointWriter`) so
        the null loop never stalls the device on saves; the queue is
        flushed on failure-saves, emergency rescues, and run exit, so
        no completed permutation is ever lost to the deferral. Applies
        only while a fault policy is active (the policy owns the
        durability contract); False keeps every save synchronous.
    hang_timeout_s : per-dispatch wall-clock budget; a dispatch exceeding
        it is abandoned (the worker thread is walked away from), completed
        work checkpointed, and the chunk re-dispatched. Set it well above
        the WORST-case dispatch time — the first chunk's jit compile
        included — or healthy dispatches get abandoned too; for
        steady-state hang detection prefer ``watchdog_action``, whose
        threshold is measured with the compile interval excluded. None
        relies on the watchdog escalation alone.
    watchdog_action : escalate the telemetry stall watchdog from warn to
        act — when a stall outlasts ``stall_action_factor`` × the measured
        steady chunk time, the watchdog thread checkpoints completed work
        and abandons the hung dispatch. Needs telemetry on (the watchdog
        is armed per null run only then).
    stall_action_factor : the act threshold, as a multiple of the steady
        chunk time (the warn threshold defaults to 10×; act defaults to
        30× — warn early, act late).
    max_abandons : hung-dispatch abandonments tolerated per run before the
        backend is presumed dead and the device-loss ladder applies.
    plan : deterministic fault-injection plan (a spec string such as
        ``"transient@128;device_lost@64"`` or a tuple of
        :class:`~netrep_tpu.utils.faults.FaultSpec`) — the test/CI harness
        that proves every recovery path; also settable via the
        ``NETREP_FAULT_PLAN`` env var. None injects nothing.
    """

    max_retries: int = 3
    backoff_base_s: float = 0.25
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    backoff_jitter: float = 0.25
    degrade_to_cpu: bool = True
    hang_timeout_s: float | None = None
    watchdog_action: bool = True
    stall_action_factor: float = 30.0
    max_abandons: int = 2
    max_mesh_rebuilds: int = 8
    async_checkpoint: bool = True
    plan: object = None

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries!r}")
        if self.max_abandons < 0:
            raise ValueError(f"max_abandons must be >= 0, got {self.max_abandons!r}")
        if self.max_mesh_rebuilds < 0:
            raise ValueError(
                "max_mesh_rebuilds must be >= 0, got "
                f"{self.max_mesh_rebuilds!r}"
            )
        for name in ("backoff_base_s", "backoff_max_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, got {getattr(self, name)!r}")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1, got {self.backoff_factor!r}"
            )
        if not 0.0 <= self.backoff_jitter < 1.0:
            raise ValueError(
                f"backoff_jitter must be in [0, 1), got {self.backoff_jitter!r}"
            )
        if self.hang_timeout_s is not None and self.hang_timeout_s <= 0:
            raise ValueError(
                f"hang_timeout_s must be > 0 or None, got {self.hang_timeout_s!r}"
            )
        if self.stall_action_factor <= 0:
            raise ValueError(
                "stall_action_factor must be > 0, got "
                f"{self.stall_action_factor!r}"
            )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Tuning knobs for the permutation engine (SURVEY.md §5).

    Attributes
    ----------
    chunk_size : permutations evaluated per device dispatch. Chunking bounds
        device memory, lets Python regain control between dispatches
        (KeyboardInterrupt → clean partial results, SURVEY.md §5 "failure
        detection"), and is the save/resume granularity.
    summary_method : 'power' (masked power iteration — MXU-friendly, the
        default) or 'eigh' (exact; used by parity tests).
    power_iters : fixed power-iteration count (static under jit). The
        default 60 is chosen from measured drift vs exact eigh at
        north-star module shapes (m=200, s=128, f32 —
        tests/test_power_vs_eigh.py): structured modules, including a
        near-degenerate two-factor case at gap ratio 0.98, agree to ~1e-5
        on every statistic by 60 iterations; null-like random modules never
        converge in *direction* (Marchenko–Pastur bulk) but their statistic
        distributions are rotation-invariant, leaving only a ≲5e-4
        systematic coherence underestimate — far below the null sd. Raising
        iterations past 60 buys nothing measurable; 40 doubles the
        coherence bias; each step is one fused m×m matmul, so 60 costs ~2%
        of the chunk on the mxu path.
    bucket_rounding : module bucket capacities are rounded up to the next
        power of two and at least this value — fewer distinct compiled
        programs (SURVEY.md §7: jit once per module-size bucket).
    dtype : matrix element dtype on device ('float32' or 'bfloat16' for the
        gather-bound large-n path; statistics always accumulate in f32).
    mesh_axis : name of the permutation data-parallel mesh axis.
    matrix_sharding : 'replicated' (matrices fit in one HBM; permutation
        axis only) or 'row' (n×n matrices row-sharded over the mesh's row
        axis with psum-assembled module gathers — SURVEY.md §5 long-context
        analogue, Config D scale).
    gather_mode : 'direct' (batched 2D advanced-index gather — exact; what
        XLA:CPU runs fastest; on TPU the per-element gather emitter crawls at
        ~60 Melem/s, round-2 measured, so it loses by ~10x there), 'mxu'
        (sorted row gather + one-hot column-select matmuls,
        :func:`netrep_tpu.ops.stats.gather_and_stats_mxu` — the TPU winner:
        XLA materializes the gathered row blocks at ~200-300 GB/s and the
        selection rides the MXU), 'fused' (Pallas one-pass kernel: per-row
        HBM→VMEM DMA + on-chip one-hot column select, no materialized row
        block or sort machinery — :mod:`netrep_tpu.ops.fused_gather`;
        composes with perm-axis meshes via shard_map, with
        ``matrix_sharding='row'`` via a per-shard kernel + psum, and with
        the multi-test engine; opt-in until TPU-measured), or 'auto'
        (mxu on TPU-like accelerators, direct on CPU). Value fidelity on
        the mxu and fused paths: XLA's
        default-precision f32 matmul truncates operands to bfloat16, so
        gathered VALUES carry up to ~4e-3 relative rounding on TPU
        (statistics attenuate this ~1/m; see ``BASELINE.md`` §precision).
    network_from_correlation : soft-threshold power β when the network is
        the WGCNA construction ``|correlation|**β``, or a ``(β, kind)``
        pair with ``kind`` in ``('unsigned', 'signed', 'signed-hybrid')``
        covering the other WGCNA adjacency types (``((1+corr)/2)**β`` and
        ``max(corr, 0)**β``). When set, the engine
        never stores or gathers the n×n network on device: network
        submatrices derive elementwise from the gathered correlation —
        halving both HBM matrix footprint and the bandwidth-bound hot
        loop's row traffic (BASELINE.md roofline). The supplied network is
        sample-checked against ``|corr|**β`` at engine build (mismatch
        raises). Ignored by ``backend='native'`` (host matrices, no HBM
        constraint) and the sparse engine (its network IS the sparse
        structure).
    perm_batch : permutations evaluated concurrently inside one chunk
        dispatch (``lax.map`` batch size), bounding the per-dispatch working
        set in HBM; the chunk itself stays one dispatch, so host round-trips
        are unaffected. None (default) resolves per gather mode: the mxu
        path sizes the batch so its (batch, Σ K_b·cap_b, n) gathered row
        blocks stay within ``mxu_batch_budget_bytes`` (≈2 at north-star
        shapes — the hand-tuned round-2 value — but much larger on smaller
        problems like Config B, whose per-permutation working set is tiny
        and which a fixed batch of 2 leaves latency-bound); the direct
        path's working set is just the (batch, K, cap, cap) submatrices, so
        it runs 64 at a time on accelerators and whole-chunk on CPU.
    mxu_batch_budget_bytes : HBM budget for the mxu gather's row-block
        intermediates used when ``perm_batch`` is None (default 2 GiB —
        reproduces the hand-tuned batch of 2 at north-star shapes and sits
        comfortably inside a 16 GiB HBM next to the stored matrices).
    autotune : persist measured steady-state chunk throughput per
        (backend, bucket shape, chunk, gather mode, perm batch) to the
        fingerprinted cache dir and reuse the best-measured ``perm_batch``
        for the same problem shape instead of re-deriving the static
        byte-budget heuristic (:mod:`netrep_tpu.utils.autotune`). With an
        empty cache the heuristic value runs unchanged (the default path
        is untouched); once a *different* batch has measured faster,
        reusing it re-partitions the chunk's ``lax.map``, which reorders
        f32 accumulation — value drift at float-rounding level (~1e-7
        relative), the same drift an explicit ``perm_batch`` change always
        caused. An explicit ``perm_batch`` is still honored verbatim (its
        throughput is recorded, so sweeps feed the cache).
    superchunk : streaming executor only (``store_nulls=False``): number of
        consecutive permutation chunks fused into ONE device dispatch via
        ``jax.lax.scan`` — the scan body evaluates one chunk (working set
        stays one chunk of HBM) and folds per-(module, statistic)
        exceedance tallies into the donated carry, so the host issues
        ~superchunk× fewer dispatches and transfers O(modules·7) counts
        per superchunk instead of O(chunk·modules·7) raw nulls. None
        (default) resolves from the persistent autotune cache's
        best-measured value for this problem shape, falling back to 8
        (:func:`netrep_tpu.utils.autotune.resolve_superchunk`). Ignored by
        the materialized (``store_nulls=True``) null loop, whose
        chunk-by-chunk output is the user-facing null array.
    """

    chunk_size: int = 128
    summary_method: str = "power"
    power_iters: int = 60
    bucket_rounding: int = 8
    #: bucket capacities above 32 round up to multiples of this (min 8,
    #: multiple of 8 for sublane alignment). The hot loop's row traffic is
    #: linear in Σcap, so finer granularity cuts the padding fraction of
    #: the bandwidth-bound gather (~16% of Σcap at north-star module sizes
    #: for 8 vs 32) — at the price of more distinct per-bucket compiled
    #: programs (compile-time only; ~4x more caps at north-star sizes).
    #: Kept at 32 until the tune sweep measures 8 faster on TPU.
    cap_granularity: int = 32
    dtype: str = "float32"
    mesh_axis: str = "perm"
    matrix_sharding: str = "replicated"
    gather_mode: str = "auto"
    #: gather_mode='fused' only: select f32 values hi/lo-split over two bf16
    #: MXU dots — ~f32-exact selection on TPU at the same one-pass HBM
    #: traffic (2x non-dominant FLOPs), vs ~10x cost for gather_mode=
    #: 'direct', the other exact-on-TPU option. No effect on CPU (exact
    #: anyway) or bf16 storage (stored values always selected bit-true).
    #: 'always' forces the split arithmetic even on CPU (interpret mode) —
    #: CI coverage of the exact engine path, not a user-facing speedup.
    fused_exact: bool | str = False
    perm_batch: int | None = None
    network_from_correlation: float | tuple | None = None
    mxu_batch_budget_bytes: int = 2 << 30
    autotune: bool = True
    superchunk: int | None = None
    #: statistics execution mode (ISSUE 8): 'xla' composes the null chunk
    #: from XLA ops (gather → seven statistic kernels → tally fold — the
    #: path every PR so far measured); 'fused' runs the Pallas mega-kernel
    #: (:mod:`netrep_tpu.ops.fused_stats`) that DMAs each module's rows
    #: HBM→VMEM once, computes all seven statistics in VMEM, and (in
    #: streaming mode) folds the (hi, lo, eff) exceedance tallies in a
    #: VMEM accumulator — O(modules·7) counts per dispatch leave the chip
    #: instead of the gathered blocks making several HBM round-trips.
    #: 'auto' resolves per backend, mirroring gather_mode's structure:
    #: TPU-like accelerators (tpu/axon) take the kernel when the summary
    #: method is the kernel-supported fixed-count power iteration; CPU
    #: (and any summary_method='eigh' run) stays on 'xla'. Explicit
    #: 'fused' requires summary_method='power' (eigh does not lower to
    #: Mosaic) and runs the Pallas interpreter on CPU — the tier-1 parity
    #: surface. Values carry the same rounding class as any re-batching
    #: (~1e-7 vs the XLA composition on CPU; MXU bf16 selection rounding
    #: on TPU, ``fused_exact`` restoring ~f32-exact selection), and the
    #: streaming↔materialized count contract is bit-exact within the mode.
    stat_mode: str = "auto"
    #: null-loop precision (ISSUE 16): 'f32' runs every permutation chunk
    #: through the full-precision chunk body (the path every earlier PR
    #: measured); 'bf16_rescue' screens each chunk with a bf16-rounded
    #: variant first — exceedance comparisons whose screened value clears
    #: the observed statistic by more than a forward-error cushion are
    #: decided as-is, and only the thin ambiguous band is re-dispatched
    #: through the existing f32 chunk program, so counts and p-values are
    #: bit-identical to the all-f32 path by construction (pinned in
    #: tests/test_screened_null.py the same way screened==unscreened tile
    #: passes were). 'auto' resolves per backend: TPU-like accelerators
    #: (tpu/axon) take the screened pass (bf16 MXU-native arithmetic,
    #: half the gather bytes), CPU stays on 'f32' (bf16 is emulated
    #: there — the screen would only add work). The screened pass needs
    #: the observed statistics up front, so runs without ``observed=``
    #: degrade to 'f32' under 'auto' and raise under explicit
    #: 'bf16_rescue'.
    null_precision: str = "auto"

    def __post_init__(self):
        if self.network_from_correlation is not None:
            # normalize early (list -> tuple so the value stays hashable for
            # jit-static threading) and fail fast on a bad kind/β
            from ..ops.stats import normalize_net_beta

            knob = self.network_from_correlation
            if isinstance(knob, list):
                knob = tuple(knob)
                object.__setattr__(self, "network_from_correlation", knob)
            beta, _kind = normalize_net_beta(knob)
            if not beta > 0:
                raise ValueError(
                    "network_from_correlation power must be > 0, got "
                    f"{beta!r}"
                )
        if self.fused_exact not in (True, False, "always"):
            raise ValueError(
                "fused_exact must be True, False, or 'always' (force the "
                f"hi/lo split even on CPU, for CI); got {self.fused_exact!r}"
            )
        if self.cap_granularity < 8 or self.cap_granularity % 8:
            raise ValueError(
                "cap_granularity must be a multiple of 8 (sublane "
                f"alignment), >= 8; got {self.cap_granularity!r}"
            )
        if self.superchunk is not None and self.superchunk < 1:
            raise ValueError(
                f"superchunk must be >= 1 or None (autotuned), got "
                f"{self.superchunk!r}"
            )
        if self.stat_mode not in ("auto", "xla", "fused"):
            raise ValueError(
                f"stat_mode must be 'auto', 'xla', or 'fused', got "
                f"{self.stat_mode!r}"
            )
        if self.stat_mode == "fused" and self.summary_method != "power":
            raise ValueError(
                "stat_mode='fused' computes coherence with the fixed-count "
                "power iteration inside the kernel; summary_method="
                f"{self.summary_method!r} is not kernel-supported — use "
                "summary_method='power' or stat_mode='xla'"
            )
        if self.null_precision not in ("auto", "f32", "bf16_rescue"):
            raise ValueError(
                "null_precision must be 'auto', 'f32', or 'bf16_rescue', "
                f"got {self.null_precision!r}"
            )

    def resolved_gather_mode(self, platform: str) -> str:
        if self.gather_mode == "auto":
            # accelerators (tpu / the axon tunnel backend) get the
            # sorted-rows+MXU path; XLA:CPU's native gather is already fast.
            # 'fused' (the Pallas one-pass kernel) must currently be opted
            # into explicitly — it becomes the auto accelerator choice once
            # TPU-measured faster than 'mxu' (benchmarks/microbench_parts).
            return "direct" if platform == "cpu" else "mxu"
        if self.gather_mode not in ("direct", "mxu", "fused"):
            raise ValueError(
                f"gather_mode must be 'auto', 'direct', 'mxu', or 'fused', "
                f"got {self.gather_mode!r}"
            )
        return self.gather_mode

    def resolved_stat_mode(self, platform: str) -> str:
        """Resolve ``stat_mode`` for a backend (see the attribute doc).
        'auto' takes the fused mega-kernel only on TPU-like accelerators
        AND only when the summary method is the kernel-supported power
        iteration — mirroring ``resolved_gather_mode``'s structure; CPU
        runs stay on the XLA composition (the kernel's interpret path is
        for parity tests and explicit opt-in, not a CPU speedup)."""
        if self.stat_mode == "auto":
            if platform in ("tpu", "axon") and self.summary_method == "power":
                return "fused"
            return "xla"
        return self.stat_mode

    def resolved_null_precision(self, platform: str) -> str:
        """Resolve ``null_precision`` for a backend (see the attribute
        doc). 'auto' takes the bf16 screen + f32 rescue only on TPU-like
        accelerators — on CPU bf16 is software-emulated, so the screened
        pass costs more than the f32 pass it would save."""
        if self.null_precision == "auto":
            return "bf16_rescue" if platform in ("tpu", "axon") else "f32"
        return self.null_precision

    def resolved_perm_batch(
        self,
        gather_mode: str,
        platform: str,
        chunk: int,
        bytes_per_perm: int | None = None,
    ) -> int:
        """``bytes_per_perm`` is the mxu path's gathered-row working set for
        ONE permutation (Σ K_b·cap_b × n × itemsize × matrices); when the
        engine supplies it, the batch fills ``mxu_batch_budget_bytes``."""
        if self.perm_batch is not None:
            return max(1, min(self.perm_batch, chunk))
        if gather_mode == "fused":
            # the fused kernel keeps row blocks in VMEM — HBM working set is
            # just the (batch, K, cap, cap) outputs; a large batch amortizes
            # kernel grid overhead across permutations
            return min(32, chunk)
        if gather_mode == "mxu":
            if bytes_per_perm and bytes_per_perm > 0:
                fit = int(self.mxu_batch_budget_bytes // bytes_per_perm)
                return max(1, min(fit, 64, chunk))
            return min(2, chunk)
        return chunk if platform == "cpu" else min(64, chunk)

    def rounded_cap(self, size: int) -> int:
        """Bucket capacity for a module of ``size`` nodes: powers of two up
        to ``max(32, cap_granularity)``, then multiples of
        ``cap_granularity`` (default 32). The dominant hot-loop cost is the
        (Σ K_b·cap_b, n) row-block traffic, linear in Σcap — multiple-of-32
        rounding wastes ≤31 padded rows per module where power-of-two
        rounding wasted up to 2x (measured ~20% less row traffic at
        north-star module sizes), while staying sublane-aligned (8) for the
        row blocks; ``cap_granularity=8`` trims the residual padding
        (~16% of Σcap at north-star sizes) for ~4x more compiled bucket
        programs. Per-bucket programs still compile once per cap."""
        g = self.cap_granularity
        cap = self.bucket_rounding
        while cap < size and cap < max(32, g):
            cap *= 2
        if size <= cap:
            return cap
        return -(-size // g) * g
