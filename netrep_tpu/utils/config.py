"""TPU engine configuration.

The reference's knobs are plain function arguments (SURVEY.md §5 "Config /
flag system" — args-only philosophy, kept for the public API); the handful of
TPU-specific tuning parameters live in this small dataclass instead of
growing the user-facing signatures.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Tuning knobs for the permutation engine (SURVEY.md §5).

    Attributes
    ----------
    chunk_size : permutations evaluated per device dispatch. Chunking bounds
        device memory, lets Python regain control between dispatches
        (KeyboardInterrupt → clean partial results, SURVEY.md §5 "failure
        detection"), and is the save/resume granularity.
    summary_method : 'power' (masked power iteration — MXU-friendly, the
        default) or 'eigh' (exact; used by parity tests).
    power_iters : fixed power-iteration count (static under jit).
    bucket_rounding : module bucket capacities are rounded up to the next
        power of two and at least this value — fewer distinct compiled
        programs (SURVEY.md §7: jit once per module-size bucket).
    dtype : matrix element dtype on device ('float32' or 'bfloat16' for the
        gather-bound large-n path; statistics always accumulate in f32).
    mesh_axis : name of the permutation data-parallel mesh axis.
    matrix_sharding : 'replicated' (matrices fit in one HBM; permutation
        axis only) or 'row' (n×n matrices row-sharded over the mesh's row
        axis with psum-assembled module gathers — SURVEY.md §5 long-context
        analogue, Config D scale).
    """

    chunk_size: int = 128
    summary_method: str = "power"
    power_iters: int = 60
    bucket_rounding: int = 8
    dtype: str = "float32"
    mesh_axis: str = "perm"
    matrix_sharding: str = "replicated"

    def rounded_cap(self, size: int) -> int:
        cap = self.bucket_rounding
        while cap < size:
            cap *= 2
        return cap
