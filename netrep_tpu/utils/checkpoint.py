"""Null-distribution checkpoint/resume (SURVEY.md §5 "Checkpoint / resume").

The reference has no checkpointing — a 100k-permutation run is
all-or-nothing. The rebuild's chunked dispatch makes save/resume trivial and
exact: the null array plus the PRNG key data fully determine the remaining
work (per-permutation keys are ``fold_in(key, i)``, independent of chunk size
and mesh — :meth:`netrep_tpu.parallel.engine.PermutationEngine.perm_keys`),
so resuming produces bit-identical results to an uninterrupted run.

Format: a single ``.npz`` with the partial null array, completion counter,
PRNG key data, and an engine fingerprint that guards against resuming onto a
different problem (wrong dataset pair, module set, or pool).
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import tempfile
import threading
import time

import numpy as np

logger = logging.getLogger("netrep_tpu")

# v2: fingerprint gained the sampled content digest — v1 checkpoints get a
# clear version error instead of a misleading "different problem" mismatch.
# v3: round-2 hot-path changes (multiple-of-32 bucket capacities, transposed
# data-matrix fingerprint arrays) alter the fingerprint for identical inputs;
# the bump turns the resulting mismatch into a clear version error.
# v4 (ISSUE 6): the fingerprint content digest moved from the engine's
# DEVICE arrays (padded/sharded per mesh shape) to the original HOST
# inputs, so a checkpoint written on an N-device mesh validates unchanged
# on any other mesh shape — including the replicated CPU rebuild — and the
# elastic shrink/grow resume needs no fingerprint-acceptance escape hatch.
_FORMAT_VERSION = 4


def _telemetry():
    """Ambient telemetry bus (save/resume events ride it when a run has
    one active — ISSUE 3); None otherwise, at the cost of one check."""
    from .telemetry import current

    return current()


def _refused(path: str, why: str) -> None:
    """A refused resume is a pinned anomaly (ISSUE 20): route it through
    the detector registry before the raise so the flight ring records
    WHY the run restarted from zero."""
    from . import detectors

    detectors.fire("checkpoint_refused", path=str(path), why=why)


def content_digest(arrays) -> str:
    """Cheap content digest of problem matrices: shapes plus a strided
    sample of up to 4096 elements per array. Catches "same module layout,
    different data" mix-ups without hashing genome-scale matrices in full
    (a completed checkpoint would otherwise be silently reused against
    changed inputs — stale nulls vs fresh observed statistics)."""
    h = hashlib.blake2b(digest_size=8)
    for a in arrays:
        if a is None:
            h.update(b"-")
            continue
        # keep device arrays on device until the small strided sample is
        # taken — digesting a sharded 20k×20k matrix must not pull the full
        # array to the host
        h.update(str(a.shape).encode() + str(a.dtype).encode())
        flat = a.reshape(-1)
        step = max(1, flat.size // 4096)
        h.update(np.asarray(flat[::step][:4096], dtype=np.float64).tobytes())
    return h.hexdigest()


def engine_fingerprint(engine) -> np.ndarray:
    """Structural + sampled-content fingerprint of a
    :class:`PermutationEngine` problem: module labels/sizes, pool, data
    presence, and a strided-sample content digest of the underlying
    matrices. Engines exposing ``fingerprint_digest()`` supply a digest
    of their original HOST inputs, computed once at construction — by
    design independent of mesh shape, matrix sharding, and padding, so
    the elastic ladder (ISSUE 6) can resume one checkpoint across any
    rebuild of the same problem. ``fingerprint_arrays()`` (the native and
    sparse engines, whose arrays never reshard) is digested directly."""
    parts = [str(_FORMAT_VERSION), str(int(engine.has_data))]
    for m in engine.modules:
        parts.append(f"{m.label}:{m.size}")
    parts.append(f"pool:{engine.pool.size}:{int(np.sum(engine.pool)) & 0xFFFFFFFF}")
    digest = getattr(engine, "fingerprint_digest", None)
    if digest is not None:
        parts.append("digest:" + str(digest()))
    else:
        arrays = getattr(engine, "fingerprint_arrays", None)
        if arrays is not None:
            parts.append("digest:" + content_digest(arrays()))
    return np.frombuffer("|".join(parts).encode(), dtype=np.uint8)


def atomic_savez(path: str, **arrays) -> None:
    """Atomically write a compressed ``.npz``: ``mkstemp`` in the target
    directory (unique across threads/processes) + ``os.replace``, so an
    interrupt or a concurrent writer never corrupts an existing file.
    Shared by checkpoints and result-object saves."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_null_checkpoint(
    path: str,
    nulls: np.ndarray,
    completed: int,
    key_data: np.ndarray,
    fingerprint: np.ndarray,
    extra: dict | None = None,
    writer: "AsyncCheckpointWriter | None" = None,
) -> None:
    """Atomically persist a (possibly partial) null array (see
    :func:`atomic_savez`). ``extra`` maps names to arrays of auxiliary
    loop state — the adaptive engine stores its sequential-stopping
    tallies and retired set here (``x_``-prefixed keys, so plain resumes
    of old checkpoints are unaffected and old builds simply ignore them).

    ``writer`` (ISSUE 6): an :class:`AsyncCheckpointWriter` takes the
    write off the loop thread — the arrays are SNAPSHOTTED here (the
    loop mutates ``nulls`` and the monitor tallies in place, so the
    background serialization must not read live buffers) and the actual
    ``atomic_savez`` happens on the writer's thread. A closed writer
    degrades to the synchronous path, so the loops' final saves after
    ``writer.close()`` stay durable without special-casing.
    """
    extras = {
        f"x_{k}": np.asarray(v) for k, v in (extra or {}).items()
    }
    if writer is not None and writer.submit(
        lambda n=np.array(nulls), e={k: np.array(v) for k, v in extras.items()}:
        _save_sync(path, n, completed, key_data, fingerprint, e)
    ):
        return
    _save_sync(path, np.asarray(nulls), completed, key_data, fingerprint,
               extras)


def _save_sync(path, nulls, completed, key_data, fingerprint, extras):
    """The actual checkpoint write — loop thread or writer thread."""
    atomic_savez(
        path,
        version=np.int64(_FORMAT_VERSION),
        nulls=nulls,
        completed=np.int64(completed),
        key_data=np.asarray(key_data),
        fingerprint=fingerprint,
        **extras,
    )
    tel = _telemetry()
    if tel is not None:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        tel.emit("checkpoint_saved", path=path, completed=int(completed),
                 bytes=int(size))


class AsyncCheckpointWriter:
    """Background checkpoint writer (ISSUE 6): a daemon thread drains a
    bounded LATEST-WINS queue of depth one — a newer snapshot of the same
    run supersedes a still-queued older one (only the newest checkpoint
    matters; writing both would just double the disk traffic), every
    write is still an atomic rename, and :meth:`flush` blocks until the
    queue is empty so failure-saves and emergency rescues stay durable
    before their error propagates. The elastic null loops use it so a
    periodic save never stalls the device between dispatches.

    Contract with the loops: periodic saves ``submit`` and return
    immediately; ``rescue()`` hooks and the run's ``finally`` call
    :meth:`flush`/:meth:`close` — after :meth:`close` further submits are
    refused (``submit`` returns False) and
    :func:`save_null_checkpoint` falls back to the synchronous path, so
    the post-loop completion save needs no special case. A failed
    background write warns (the loop must survive a full disk exactly
    like the telemetry sink does) and the next save tries again.
    """

    def __init__(self, telemetry=None):
        self.telemetry = telemetry
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = None
        self._busy = False
        self._closed = False
        self._writes = 0
        self._superseded = 0
        self._thread: threading.Thread | None = None

    def submit(self, fn) -> bool:
        """Queue one checkpoint write (a zero-arg callable over already-
        snapshotted arrays). Returns False when the writer is closed —
        the caller performs the write synchronously instead."""
        with self._cond:
            if self._closed:
                return False
            if self._pending is not None:
                self._superseded += 1  # latest wins
            self._pending = fn
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="netrep-ckpt-writer", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return True

    def _loop(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                fn = self._pending
                self._pending = None
                if fn is None:  # closed with nothing queued
                    return
                self._busy = True
            try:
                fn()
                with self._lock:
                    self._writes += 1
            except BaseException as e:
                if not isinstance(e, Exception):
                    # KeyboardInterrupt / SimulatedCrash-class unwinds
                    # must kill this thread like they kill the process —
                    # absorbing one as "a failed write" would let a crash
                    # drill report a healthy writer (ISSUE 12 taxonomy)
                    raise
                logger.warning(
                    "async checkpoint write failed; the next save will "
                    "retry", exc_info=True,
                )
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def flush(self) -> float:
        """Block until the queue is drained and no write is in flight;
        returns the seconds waited. Called by emergency rescues and the
        failure-save paths — a checkpoint an error handler just saved
        must be ON DISK before the error reaches the resume logic."""
        t0 = time.monotonic()
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait(timeout=0.1)
        return time.monotonic() - t0

    def close(self) -> None:
        """Flush, stop the thread, and emit one ``checkpoint_async_flush``
        event summarizing the writer's life (writes performed, superseded
        queue entries, final flush wait) — the pinned telemetry record
        that the async path was active and drained cleanly."""
        waited = self.flush()
        with self._cond:
            already = self._closed
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
            # snapshot the tallies under the lock: the writer thread may
            # still be mid-_loop until the join below (ISSUE 12
            # thread-shared-state discipline)
            writes, superseded = self._writes, self._superseded
        if thread is not None:
            thread.join(timeout=2.0)
        if already:
            return
        tel = self.telemetry if self.telemetry is not None else _telemetry()
        if tel is not None:
            tel.emit(
                "checkpoint_async_flush", writes=writes,
                superseded=superseded, waited_s=float(waited),
            )


def load_null_checkpoint(path: str) -> dict | None:
    """Load a checkpoint, or ``None`` when the file doesn't exist."""
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        if "version" not in z.files:
            _refused(path, "no_version_marker")
            raise ValueError(
                f"{path!r} is not a null checkpoint (no version marker — "
                "saved PreservationResult files and other .npz files cannot "
                "be resumed from)"
            )
        if int(z["version"]) != _FORMAT_VERSION:
            _refused(path, "format_version")
            raise ValueError(
                f"checkpoint {path!r} has format version {int(z['version'])}, "
                f"this build reads version {_FORMAT_VERSION}"
            )
        return {
            "nulls": z["nulls"],
            "completed": int(z["completed"]),
            "key_data": z["key_data"],
            "fingerprint": z["fingerprint"],
            # auxiliary loop state (adaptive tallies/retired set); empty
            # for checkpoints written by fixed-n runs
            "extras": {
                k[2:]: z[k] for k in z.files if k.startswith("x_")
            },
        }


#: active degraded-rebuild acceptance scopes. SCOPE NOTE (ISSUE 7 closes
#: the long-lived known-gap comment here): the condition this scope was
#: added for — a device-loss → CPU rebuild changing the fingerprint
#: because row-sharded matrices were padded/sharded into the digest — no
#: longer occurs on the built-in engines: format v4 (ISSUE 6) digests
#: the ORIGINAL host inputs at construction, so fingerprints are
#: mesh-shape-independent and an elastic/CPU rebuild validates cleanly
#: (PR 5's acceptance test now pins ZERO ``fingerprint_degraded_accept``
#: events on that path). The scope stays as a BELT for engines whose
#: identity is still layout-sensitive (third-party engines exposing only
#: ``fingerprint_arrays()`` over device buffers). Within a scope a
#: FINGERPRINT mismatch is tolerated with a ``fingerprint_degraded_accept``
#: event + warning instead of a refusal; key/seed mismatches still ALWAYS
#: raise — splicing two null streams is never right, degraded or not
#: (pinned in tests/test_checkpoint.py).
_DEGRADED_ACCEPT: list[str] = []


@contextlib.contextmanager
def accept_degraded_fingerprint(reason: str = "degraded_rebuild"):
    """Scope in which :func:`validate_identity` tolerates a fingerprint
    mismatch (see :data:`_DEGRADED_ACCEPT`). Entered by
    ``models/preservation.py`` around the post-``degrade_to_cpu`` resume
    only — the acceptance is per-rebuild, never process-global."""
    _DEGRADED_ACCEPT.append(str(reason))
    try:
        yield
    finally:
        _DEGRADED_ACCEPT.pop()


def validate_identity(
    ckpt: dict,
    key_data: np.ndarray,
    fingerprint: np.ndarray,
    path: str,
) -> None:
    """Problem/seed identity checks shared by the materialized and
    streaming-counts resume paths (the streaming path has no null array to
    reshape, so :func:`validate_resume` splits in two): raises with a
    specific message on any mismatch — except a fingerprint mismatch
    inside an :func:`accept_degraded_fingerprint` scope, which is accepted
    explicitly (event + warning) because the degraded CPU rebuild changed
    the engine's matrix layout, not the problem."""
    fp = ckpt["fingerprint"]
    if fp.shape != fingerprint.shape or not np.array_equal(fp, fingerprint):
        if not _DEGRADED_ACCEPT:
            _refused(path, "fingerprint_mismatch")
            raise ValueError(
                f"checkpoint {path!r} was written for a different problem "
                "(module set, sizes, pool, data presence, or store_nulls "
                "mode differ); refusing to resume — delete the file or "
                "point elsewhere"
            )
        reason = _DEGRADED_ACCEPT[-1]
        tel = _telemetry()
        if tel is not None:
            tel.emit(
                "fingerprint_degraded_accept", path=path, reason=reason,
                completed=int(ckpt["completed"]),
            )
        logger.warning(
            "checkpoint %r fingerprint mismatches the rebuilt engine "
            "(expected after a %s rebuild: matrix sharding/padding "
            "changed, the problem did not); accepting the resume — the "
            "PRNG key/seed is still verified below", path, reason,
        )
    kd = np.asarray(ckpt["key_data"])
    if kd.shape != np.asarray(key_data).shape or not np.array_equal(kd, key_data):
        _refused(path, "prng_key_mismatch")
        raise ValueError(
            f"checkpoint {path!r} was written with a different PRNG key/seed; "
            "resuming would splice two different null distributions — use the "
            "original seed or delete the checkpoint"
        )
    tel = _telemetry()
    if tel is not None:
        # identity validated on BOTH resume paths (materialized and
        # streaming) — this is the one shared site, so the resume event
        # can never be emitted for a refused checkpoint
        tel.emit("checkpoint_resumed", path=path,
                 completed=int(ckpt["completed"]))


def validate_resume(
    ckpt: dict,
    n_perm: int,
    key_data: np.ndarray,
    fingerprint: np.ndarray,
    path: str,
    perm_axis: int = 0,
) -> tuple[np.ndarray, int]:
    """Check a loaded checkpoint against the current run; returns
    ``(nulls_init, start_perm)`` ready for
    :meth:`PermutationEngine.run_null`. Raises with a specific message on any
    mismatch (SURVEY.md §2.1: informative errors are part of the surface)."""
    validate_identity(ckpt, key_data, fingerprint, path)
    nulls = ckpt["nulls"]
    if nulls.shape[perm_axis] < n_perm:
        shape = list(nulls.shape)
        shape[perm_axis] = n_perm
        grown = np.full(shape, np.nan)
        sel = [slice(None)] * nulls.ndim
        sel[perm_axis] = slice(0, nulls.shape[perm_axis])
        grown[tuple(sel)] = nulls
        nulls = grown
    elif nulls.shape[perm_axis] > n_perm:
        # shrinking run: honor the caller's (n_perm, ...) shape contract
        sel = [slice(None)] * nulls.ndim
        sel[perm_axis] = slice(0, n_perm)
        nulls = nulls[tuple(sel)].copy()
    completed = min(int(ckpt["completed"]), n_perm)
    return nulls, completed
