"""Backend resolution against a flaky TPU tunnel.

The driver image pins ``JAX_PLATFORMS=axon`` (a tunneled TPU backend) and its
``sitecustomize`` registers the plugin at interpreter startup — so the env
var is snapshotted before user code runs, and a later ``jax.devices()`` call
dials the tunnel even if the env var is changed. When the tunnel is down the
dial HANGS indefinitely instead of erroring (round-2 driver artifacts went
red on exactly this). Two rules follow:

1. Only ``jax.config.update("jax_platforms", ...)`` actually redirects the
   backend after startup; the env var alone does not.
2. The only safe liveness check is a probe in a killable subprocess.

This module is the single home of those heuristics (bench.py and
``__graft_entry__`` both consume it — they drifted as separate copies in
round 2, flagged in review).

Residual race: a probe is stale the moment it returns — a tunnel that dies
between the probe and the caller's first real device use still hangs
in-process. The window is seconds; callers that cannot tolerate it must run
their device work under their own wall-clock budget (the driver does).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time

logger = logging.getLogger("netrep_tpu")


def _telemetry():
    """Ambient telemetry bus (probe/fallback decisions ride it when one is
    active — ISSUE 3: the round-5 CPU fallback was *unannounced*)."""
    from .telemetry import current

    return current()


def tunnel_expected() -> bool:
    """Whether the default backend would dial the axon TPU tunnel."""
    want = os.environ.get("JAX_PLATFORMS", "")
    return "axon" in want or (not want and os.path.exists("/root/.axon_site"))


def honor_explicit_platform():
    """If ``JAX_PLATFORMS`` names an explicit non-axon platform, force it via
    the live config (rule 1 above) and return its devices, falling back to
    CPU when that platform is unavailable — never automatic selection, which
    would dial the axon plugin. Returns ``None`` when no explicit non-axon
    platform is set (callers continue with their own tunnel policy)."""
    import jax

    want = os.environ.get("JAX_PLATFORMS", "")
    if not want or "axon" in want:
        return None
    jax.config.update("jax_platforms", want)
    try:
        return jax.devices()
    except RuntimeError:
        tel = _telemetry()
        if tel is not None:
            tel.emit("backend_fallback", reason="explicit_unavailable",
                     wanted=want, forced="cpu")
        logger.warning(
            "explicit platform %r unavailable; falling back to CPU", want
        )
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()


def host_cpu_fingerprint() -> str:
    """Short stable fingerprint of this host's CPU instruction-set features.

    XLA:CPU AOT executables compiled for machine features the executing
    host lacks can SIGILL — the real cross-machine risk behind round 4's
    ``cpu_aot_loader`` errors. Embedding this fingerprint in the cache
    path guarantees hosts with different REAL feature sets never exchange
    AOT entries. What it cannot silence: XLA also records compile-time
    pseudo-features (``+prefer-no-scatter``/``+prefer-no-gather``) that
    host detection never reports, so the loader still logs a
    machine-feature mismatch on every reuse — including same-host, where
    it is cosmetic (no pseudo-feature can SIGILL). Paths whose output an
    artifact-checker reads (``dryrun_multichip``) therefore skip the
    cache entirely; tests and bench tolerate the log noise for the
    warm-cache win.
    """
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    basis = f"{platform.machine()}|{feats}"
    return hashlib.sha256(basis.encode()).hexdigest()[:12]


def enable_persistent_cache(repo_root: str | None = None) -> None:
    """Point JAX's persistent compilation cache at the repo-local
    ``.jax_cache/<cpu-fingerprint>`` dir (gitignored). Shared by
    ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip`` so the
    two bootstraps cannot diverge (dir or thresholds). A miss compiles
    exactly as before. The per-host-CPU subdir removes the cross-machine
    AOT reuse that risks SIGILL (see :func:`host_cpu_fingerprint` —
    including what it deliberately does NOT try to silence)."""
    import jax

    if repo_root is None:
        # this file lives at netrep_tpu/utils/backend.py — repo root is 3 up
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(repo_root, ".jax_cache", host_cpu_fingerprint()),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def probe_default_backend(timeout: float) -> str:
    """Probe ``jax.devices()`` in a killable subprocess.

    Returns ``"ok"`` (responsive), ``"error"`` (fast nonzero exit — e.g.
    plugin registration failure; the in-process call would *error*, not
    hang), or ``"timeout"`` (hung-dead tunnel). The outcome and probe
    duration are emitted as a ``backend_probe`` telemetry event when a bus
    is active — dead-tunnel probes ate 120 s of the round-5 measurement
    windows without leaving a machine-readable trace."""
    t0 = time.perf_counter()
    try:
        rc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True,
        ).returncode
        outcome = "ok" if rc == 0 else "error"
    except subprocess.TimeoutExpired:
        outcome = "timeout"
    tel = _telemetry()
    if tel is not None:
        tel.emit("backend_probe", outcome=outcome,
                 s=time.perf_counter() - t0, timeout_s=float(timeout))
    return outcome


def device_inventory(devices) -> list[str]:
    """Compact ``platform:id`` strings for a device list — the inventory
    the elastic-ladder events (``mesh_shrunk``/``mesh_grown``/
    ``degraded_to_cpu``) carry so an offline reader can see exactly which
    devices were freed and which survived each rebuild."""
    out = []
    for d in devices or ():
        plat = getattr(d, "platform", None) or type(d).__name__
        out.append(f"{plat}:{getattr(d, 'id', '?')}")
    return out


def enumerate_survivors(mesh, error=None) -> tuple[list, list]:
    """``(survivors, lost)`` device lists after a device-loss-class
    failure on ``mesh`` — the decision input of the elastic ladder
    (ISSUE 6): any survivor ⇒ shrink the mesh onto them; none ⇒ the CPU
    rung.

    Attribution comes from the error chain: a failure whose cause carries
    an ``n_lost`` attribute (the injected partial loss; a coordination
    layer annotating real losses can use the same contract) loses that
    many devices off the front of the mesh's device list — ``n_lost=None``
    means half, the deterministic drill default. An UNATTRIBUTED device
    loss presumes the whole client is gone (a dead TPU runtime takes
    every device it owns with it), which keeps the pre-elastic behavior:
    straight to CPU. No liveness probing happens here — on tunneled
    backends a probe of a half-dead client can hang, and the ladder must
    decide quickly."""
    if mesh is None:
        return [], []
    devices = [d for d in mesh.devices.flat]
    e = error
    while e is not None:
        if hasattr(e, "n_lost"):
            n_lost = e.n_lost
            if n_lost is None:
                n_lost = (len(devices) + 1) // 2
            n_lost = min(max(1, int(n_lost)), len(devices))
            return devices[n_lost:], devices[:n_lost]
        e = e.__cause__
    return [], devices


def announce_mesh_shrunk(reason: str, surviving, freed, **context) -> None:
    """Structurally announce a mesh-shrink rebuild (the rung ABOVE CPU
    degradation): one ``mesh_shrunk`` event carrying the freed and
    surviving device inventories plus caller context, and one logger
    warning. The caller rebuilds its engine over the survivors and
    resumes from the failure-saved checkpoint — bit-identical, because
    per-permutation keys depend only on ``(key, index)``."""
    tel = _telemetry()
    if tel is not None:
        tel.emit(
            "mesh_shrunk", reason=reason,
            surviving=device_inventory(surviving),
            freed=device_inventory(freed),
            n_surviving=len(surviving), n_freed=len(freed), **context,
        )
    logger.warning(
        "mesh shrunk (%s): %d device(s) lost, rebuilding over the %d "
        "survivor(s) and resuming from checkpoint", reason, len(freed),
        len(surviving),
    )


def announce_mesh_grown(surviving, restored, **context) -> None:
    """Structurally announce a mesh grow-back (capacity returned): one
    ``mesh_grown`` event with the restored inventory, one logger info."""
    tel = _telemetry()
    if tel is not None:
        tel.emit(
            "mesh_grown", surviving=device_inventory(surviving),
            restored=device_inventory(restored),
            n_devices=len(surviving), **context,
        )
    logger.warning(
        "mesh grown back to %d device(s) (%d restored); resuming from "
        "checkpoint", len(surviving), len(restored),
    )


def degrade_to_cpu(reason: str, **context) -> None:
    """Mid-run CPU degradation (ISSUE 4; since ISSUE 6 the FINAL rung of
    the elastic fault ladder, taken only when zero accelerator devices
    survive): force the CPU platform via the live config (rule 1 above —
    the env var alone would not redirect an already-started process) and
    announce it, structurally (one ``degraded_to_cpu`` event carrying
    ``reason`` + caller context, including the freed device inventory
    when the caller supplies one) and via the logger. Callers rebuild
    their engines afterwards and resume from the failure-saved
    checkpoint; per-permutation keys depend only on ``(key, index)``, so
    the resumed CPU run continues the same null stream."""
    import jax

    tel = _telemetry()
    if tel is not None:
        tel.emit("degraded_to_cpu", reason=reason, **context)
    logger.warning(
        "degrading to the CPU platform (%s); engines will be rebuilt on "
        "CPU and resumed from checkpoint", reason,
    )
    jax.config.update("jax_platforms", "cpu")


def resolve_backend_or_cpu(probe_timeout: float | None = None) -> None:
    """Make the next ``jax.devices()`` call hang-safe: honor an explicit
    non-TPU platform, keep a probed-live tunnel, and force the CPU platform
    (live config, per rule 1 above) in every case that cannot be proven
    responsive. Used by ``__graft_entry__`` — the driver's compile-check
    entries must complete regardless of tunnel state. The probe budget is
    overridable via ``NETREP_BACKEND_PROBE_TIMEOUT`` (CI shortens it; the
    driver keeps the default)."""
    import jax

    if probe_timeout is None:
        try:
            probe_timeout = float(
                os.environ.get("NETREP_BACKEND_PROBE_TIMEOUT", "90")
            )
        except ValueError:
            probe_timeout = 90.0
    if honor_explicit_platform() is not None:
        return
    if tunnel_expected():
        outcome = probe_default_backend(probe_timeout)
        if outcome != "ok":
            # announce the fallback (ISSUE 3: the round-5 CPU drop was
            # silent) — once via the logger, structurally via telemetry
            tel = _telemetry()
            if tel is not None:
                tel.emit("backend_fallback", reason=f"probe_{outcome}",
                         forced="cpu", probe_timeout_s=float(probe_timeout))
            logger.warning(
                "TPU tunnel probe result %r (budget %.0fs); forcing the "
                "CPU platform for this process", outcome, probe_timeout,
            )
            jax.config.update("jax_platforms", "cpu")
