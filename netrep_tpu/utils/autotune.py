"""Persistent per-(backend, bucket-caps, chunk) throughput autotune cache.

The engine's per-dispatch permutation batch (``EngineConfig.perm_batch``) is
derived from a static byte-budget heuristic
(:meth:`~netrep_tpu.utils.config.EngineConfig.resolved_perm_batch`). That
heuristic cannot see what the box is actually delivering — the round-5
driver bench drifted 752→982 s on the identical CPU-fallback config with no
code change, and nothing recorded per-chunk throughput to tell contention
from regression. This module closes the loop: the chunked null loop records
the *measured* steady-state permutations/second for the (backend, bucket
shape, chunk, gather mode, perm batch) it ran, and the next engine build
with the same key reuses the best-measured batch instead of re-deriving the
heuristic value.

Storage is one JSON file under the same fingerprinted cache dir as the
persistent XLA compile cache (``.jax_cache/<cpu-fingerprint>/``), so
entries never migrate across hosts with different real machine features —
the same isolation rule the AOT cache needs
(:func:`netrep_tpu.utils.backend.host_cpu_fingerprint`). Writes are atomic
(tempfile + ``os.replace``) and loads are tolerant: a corrupt or
foreign-format file is treated as empty, never raised to the engine's hot
path. Reusing a different measured batch re-partitions the chunk's
``lax.map`` and thus reorders f32 accumulation — value drift at
float-rounding level only (~1e-7 relative), identical in kind to what an
explicit ``perm_batch`` change always caused; an empty cache leaves the
heuristic path untouched.
"""

from __future__ import annotations

import json
import os
import tempfile

#: keep this many most-recent measurements per (key, setting) — enough to
#: smooth box-contention noise without the file growing unboundedly
_KEEP = 8
_FORMAT = 1


def _telemetry():
    """Ambient telemetry bus: cache hits/misses and recorded perms/s ride
    it when a run has one active (ISSUE 3 — nothing previously recorded
    whether a run used a measured or heuristic setting)."""
    from .telemetry import current

    return current()


def default_path() -> str:
    """Autotune store beside the persistent compile cache: the repo-local
    ``.jax_cache/<cpu-fingerprint>/autotune.json``."""
    from .backend import host_cpu_fingerprint

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(
        repo_root, ".jax_cache", host_cpu_fingerprint(), "autotune.json"
    )


def make_key(backend: str, gather_mode: str, caps: str, chunk: int,
             extra: str = "") -> str:
    """Cache key for one engine problem shape: backend × gather mode ×
    bucket-cap signature × chunk size (+ wrapper-specific ``extra``, e.g.
    the multi-test dataset count)."""
    key = f"{backend}|{gather_mode}|caps:{caps}|chunk:{int(chunk)}"
    return key + (f"|{extra}" if extra else "")


class AutotuneCache:
    """Tiny persistent map ``key -> {setting: [perms_per_sec, ...]}``.

    ``setting`` is the tunable value as a string (currently the resolved
    ``perm_batch``). Concurrent writers (parallel test processes) race
    benignly: each read-merge-replace keeps its own measurements plus
    whatever the last writer stored; losing a few samples only delays
    convergence.
    """

    def __init__(self, path: str | None = None):
        self.path = path or default_path()

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                data = json.load(f)
            if data.get("format") != _FORMAT or not isinstance(
                data.get("entries"), dict
            ):
                return {}
            return data["entries"]
        except (OSError, ValueError):
            return {}

    def record(self, key: str, setting: int, perms_per_sec: float) -> None:
        """Append one steady-state throughput measurement (best-effort: an
        unwritable cache dir silently skips — tuning is never load-bearing)."""
        if not perms_per_sec > 0:
            return
        tel = _telemetry()
        if tel is not None:
            tel.emit("autotune_record", key=key, setting=int(setting),
                     perms_per_sec=float(perms_per_sec))
        entries = self._load()
        samples = entries.setdefault(key, {}).setdefault(str(int(setting)), [])
        samples.append(round(float(perms_per_sec), 3))
        del samples[:-_KEEP]
        try:
            d = os.path.dirname(self.path)
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
            with os.fdopen(fd, "w") as f:
                json.dump({"format": _FORMAT, "entries": entries}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass

    def best_setting(self, key: str) -> int | None:
        """Setting with the best median recorded throughput for ``key``, or
        None when nothing has been measured yet (callers fall back to the
        static heuristic). Median, not max: a single contention-free lucky
        sample must not pin a batch size forever."""
        entries = self._load().get(key)
        if not entries:
            return None
        def med(v):
            s = sorted(v)
            return s[len(s) // 2]
        try:
            return int(max(entries, key=lambda k: med(entries[k])))
        except (ValueError, TypeError):
            return None

    def throughput(self, key: str, setting: int) -> list[float]:
        """Recorded samples for (key, setting) — diagnostics/tests."""
        return list(self._load().get(key, {}).get(str(int(setting)), []))


def resolve_perm_batch(config, key: str, heuristic: int):
    """Autotuned perm-batch resolution shared by the engines: an explicit
    ``config.perm_batch`` or ``autotune=False`` keeps the static value;
    otherwise the best-measured setting for ``key`` (if any) replaces the
    byte-budget heuristic. Returns ``(perm_batch, cache_or_None)`` — the
    cache handle is what the run loop records the measured throughput to.
    """
    if not getattr(config, "autotune", False):
        return heuristic, None
    cache = AutotuneCache()
    if config.perm_batch is not None:
        # explicit setting: honor it (it already rode the resolved value in
        # ``heuristic``) but still record its measured throughput, so batch
        # sweeps populate the cache with real alternatives
        return heuristic, cache
    best = cache.best_setting(key)
    _emit_lookup("perm_batch", key, best, heuristic)
    return (best if best is not None and best > 0 else heuristic), cache


def _emit_lookup(kind: str, key: str, best, fallback) -> None:
    """One ``autotune_hit``/``autotune_miss`` event per cache consult."""
    tel = _telemetry()
    if tel is None:
        return
    if best is not None and best > 0:
        tel.emit("autotune_hit", kind=kind, key=key, setting=int(best))
    else:
        tel.emit("autotune_miss", kind=kind, key=key,
                 fallback=int(fallback))


#: static fallback for the streaming executor's superchunk when nothing has
#: been measured yet: 8 chunks per dispatch amortizes the ~1 s tunneled
#: dispatch latency ~8× while the scan carry keeps the working set at one
#: chunk of HBM; on CPU the scan is the same compute with fewer Python
#: round-trips, so the value is safe as a universal default.
DEFAULT_SUPERCHUNK = 8


def resolve_superchunk(config, key: str, default: int = DEFAULT_SUPERCHUNK):
    """Autotuned superchunk resolution for the streaming executor
    (:meth:`netrep_tpu.parallel.engine.PermutationEngine.run_null_streaming`):
    an explicit ``config.superchunk`` is honored verbatim; otherwise the
    best-measured setting recorded for ``key`` — perms/s per (backend,
    bucket shape, chunk, gather mode, *superchunk*) — replaces the static
    default. Returns ``(superchunk, cache_or_None)``; the streaming loop
    records its measured steady-state perms/s back to the cache handle, so
    superchunk sweeps (and ordinary runs) converge on the fastest fused
    dispatch depth per problem shape. ``autotune=False`` disables both the
    lookup and the recording.
    """
    explicit = getattr(config, "superchunk", None)
    if not getattr(config, "autotune", False):
        return (max(1, int(explicit)) if explicit is not None else default,
                None)
    cache = AutotuneCache()
    if explicit is not None:
        # explicit setting: honor it but record its throughput, so sweeps
        # populate the cache with real alternatives (same contract as
        # resolve_perm_batch)
        return max(1, int(explicit)), cache
    best = cache.best_setting(key)
    _emit_lookup("superchunk", key, best, default)
    return (best if best is not None and best > 0 else default), cache


def peek_superchunk(config, key: str,
                    default: int = DEFAULT_SUPERCHUNK) -> int:
    """The superchunk :func:`resolve_superchunk` WILL resolve for
    ``(config, key)``, without emitting autotune telemetry or returning a
    recording handle — the AOT program builder (ISSUE 15) needs the value
    to shape the superchunk program's abstract signature before the
    streaming run resolves it for real."""
    explicit = getattr(config, "superchunk", None)
    if explicit is not None:
        return max(1, int(explicit))
    if not getattr(config, "autotune", False):
        return default
    best = AutotuneCache().best_setting(key)
    return best if best is not None and best > 0 else default


#: static fallback for the atlas tile pass's tile edge (ISSUE 9) when
#: nothing has been measured yet: a 1024-row block keeps the per-dispatch
#: working set (one (edge, n) correlation strip + its derived-net twin in
#: f32) near ~1 GB at the 100k-gene atlas shape — comfortably inside one
#: HBM beside the O(n·s) data columns — while each tile is still a
#: (1024, s)×(s, 1024) MXU matmul deep enough to be compute-bound.
DEFAULT_TILE_EDGE = 1024


def resolve_tile_edge(config, key: str, explicit: int | None = None,
                      default: int = DEFAULT_TILE_EDGE):
    """Autotuned tile-edge resolution for the atlas tiled network plane
    (:mod:`netrep_tpu.atlas.builder` — ISSUE 9, beside the superchunk
    entry): an ``explicit`` edge is honored verbatim (its measured
    throughput is still recorded, so edge sweeps feed the cache); else the
    best-measured edge for ``key`` — gene columns/s per (backend,
    atlas-tiles, problem shape, *edge*) — replaces the static default.
    Returns ``(edge, cache_or_None)``; the tile pass records its measured
    steady-state columns/s back to the handle. ``config.autotune=False``
    disables both lookup and recording, exactly like the perm-batch and
    superchunk resolutions."""
    if not getattr(config, "autotune", False):
        return (max(8, int(explicit)) if explicit is not None else default,
                None)
    cache = AutotuneCache()
    if explicit is not None:
        return max(8, int(explicit)), cache
    best = cache.best_setting(key)
    _emit_lookup("tile_edge", key, best, default)
    return (best if best is not None and best >= 8 else default), cache


#: static fallback for the exact-tile-screening coarse level (ISSUE 11)
#: when nothing has been measured yet: groups of 8 tiles keep the coarse
#: bound table T/8 entries per row block (one fused prune decision per
#: ~8·edge columns) while a surviving group still refines into at most 8
#: per-tile bounds — and 8 tiles per worklist dispatch amortizes dispatch
#: latency the same way the superchunk default does for the null loops.
DEFAULT_SUPERTILE = 8


def resolve_supertile(config, key: str, explicit: int | None = None,
                      default: int = DEFAULT_SUPERTILE):
    """Autotuned super-tile factor for the atlas screening pass
    (:mod:`netrep_tpu.atlas.builder` — ISSUE 11, beside
    :func:`resolve_tile_edge`): how many consecutive tiles share one
    coarse bound (and one worklist dispatch) in the two-resolution screen.
    An ``explicit`` factor is honored verbatim (its measured columns/s is
    still recorded, so factor sweeps feed the cache); else the
    best-measured factor for ``key`` replaces the static default. Returns
    ``(factor, cache_or_None)``; ``config.autotune=False`` disables both
    lookup and recording, exactly like the tile-edge resolution."""
    if not getattr(config, "autotune", False):
        return (max(1, int(explicit)) if explicit is not None else default,
                None)
    cache = AutotuneCache()
    if explicit is not None:
        return max(1, int(explicit)), cache
    best = cache.best_setting(key)
    _emit_lookup("supertile", key, best, default)
    return (best if best is not None and best >= 1 else default), cache


def resolve_fused_rowblock(config, key: str):
    """Autotuned row-block for the fused-statistics mega-kernel's DMA/
    select grid (ISSUE 8; :func:`netrep_tpu.ops.fused_stats.
    resolve_row_block` applies the returned override after sublane
    alignment and the VMEM budget guard). Nothing measured yet → ``None``
    (the kernel's minimal-padding heuristic runs unchanged). The streaming
    loop records its measured perms/s against the resolved block via the
    same ``record_stream_throughput`` callback that feeds the superchunk
    entry, so row-block sweeps converge per problem shape exactly like
    perm-batch and superchunk do. Returns ``(row_block_or_None,
    cache_or_None)``."""
    if not getattr(config, "autotune", False):
        return None, None
    cache = AutotuneCache()
    best = cache.best_setting(key)
    _emit_lookup("fused_rowblock", key, best, 0)
    return (best if best is not None and best >= 8 else None), cache
