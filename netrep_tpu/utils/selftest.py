"""On-device numerical self-check for new deployments.

A user bringing this framework up on unfamiliar hardware (a new TPU
generation, a different driver/libtpu, an experimental backend like the
axon tunnel) needs one call that answers "does this device compute what
the NumPy oracle computes?" before trusting a 100k-permutation run.
:func:`selftest` builds deterministic multi-bucket toy problems, runs the
observed pass and a small permutation null on the current default backend,
and cross-checks both against the pure-NumPy oracle — including
reconstructing one permutation from the documented seeding contract
(``fold_in(key, i)`` → ``jax.random.permutation`` over the pool), so the
draw → slice → gather → statistics path is validated end-to-end on the
device, not just the kernels (the same contract
``tests/test_engine.py::test_null_chunk_matches_oracle_reconstruction``
pins on CPU).

The reference has no analogue (its single backend is the host CPU); this
is deployment tooling a multi-backend framework owes its users.
"""

from __future__ import annotations

import time

import numpy as np


#: statistic-level tolerance where MXU truncation applies: TPU's
#: default-precision f32 matmuls truncate gather operands to bfloat16
#: (~4e-3 relative on values, attenuated ~1/m by the statistics —
#: BASELINE.md §Precision). Real breakage (wrong indices, bad collective,
#: miscompiled kernel) shows up orders of magnitude above this.
_ATOL_MXU = 2e-2
#: tolerance everywhere MXU bf16 truncation is NOT real device behavior:
#: agreement with the oracle is ~1e-5 on exact-f32-matmul backends, so a
#: uniform MXU-sized bound would wave a 100× device-math regression
#: through (VERDICT r4 item 8; ADVICE r5 closed the same hole for unknown
#: accelerators — e.g. GPU — which previously inherited the loose tier).
_ATOL_EXACT = 1e-4
#: backends KNOWN to truncate f32 matmul operands to bf16 and therefore
#: granted the loose tier: TPU proper, and the axon tunnel (a TPU behind a
#: gRPC dial — same MXU). Everything else, including backends this list
#: has never seen, defaults to the tight tier; a genuinely-truncating new
#: accelerator then fails loudly and gets added here deliberately.
_TRUNCATING_BACKENDS = ("tpu", "axon")


def tolerance_for(backend: str) -> float:
    """The tier table, as one auditable function (ISSUE 12 closes the
    ADVICE r5 finding): loose MXU tolerance ONLY for backends known to
    truncate f32 matmul operands to bf16; every other backend — cuda,
    rocm, cpu, and accelerators this code has never met — is held to the
    exact-f32 tier so a device-math regression fails loudly instead of
    hiding under hardware-rounding headroom. Pinned by
    tests/test_selftest.py::test_tolerance_tier_table."""
    return _ATOL_MXU if backend in _TRUNCATING_BACKENDS else _ATOL_EXACT

#: (module sizes, n nodes, n samples) per validated problem, ordered
#: smallest-problem first. The first straddles the 32-cap bucket boundary
#: so at least two compiled bucket programs execute; the second is larger
#: (different caps, different one-hot/matmul tilings) so a shape-dependent
#: miscompile cannot hide behind the small shape (VERDICT r4 item 8).
#: ``max_shapes`` keeps the LARGEST shapes (the tail of this tuple) — see
#: :func:`selftest`.
_SHAPES = (
    ((40, 18, 9), 96, 24),
    ((72, 40, 21), 192, 32),
)


def selftest(n_perm: int = 32, seed: int = 0, verbose: bool = True,
             mesh=None, max_shapes: int | None = None) -> dict:
    """Run the on-device numerical self-check; return a summary dict.

    With ``mesh`` (a :func:`netrep_tpu.make_mesh` mesh) the null runs
    sharded — permutation chunks over the ``perm`` axis, and with
    ``n_row_shards > 1`` the matrices row-sharded with collective module
    gathers — so a pod deployment can validate its ICI/DCN collective
    path against the same oracle before a large run, not just one chip's
    arithmetic.

    The pass tolerance is backend-conditional: CPU (exact f32 matmuls)
    is held to ~1e-4; the ~2e-2 bound applies only where TPU MXU bf16
    truncation is real device behavior, so a genuine device-math
    regression cannot hide under hardware-rounding headroom.

    ``max_shapes`` bounds how many of the validated problem shapes run
    (None = all), keeping the LARGEST shapes: a time-boxed on-chip gate
    (the watcher's, inside a ~5-7 min tunnel window) passes
    ``max_shapes=1`` and must not be satisfiable by the small shape alone
    — a shape-dependent miscompile (tiling, padding) hides exactly there
    (VERDICT r5 weak #5). Multi-shape coverage still holds on every CPU
    CI run.

    Raises ``RuntimeError`` with the failing comparison when the device
    disagrees with the NumPy oracle beyond those tolerances.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import oracle
    from ..parallel.engine import ModuleSpec, PermutationEngine
    from .config import EngineConfig

    if n_perm < 1:
        raise ValueError(f"n_perm must be >= 1, got {n_perm}")
    t_start = time.perf_counter()
    device = str(jax.devices()[0])
    backend = jax.default_backend()
    atol = tolerance_for(backend)

    if max_shapes is not None and max_shapes < 1:
        raise ValueError(f"max_shapes must be >= 1 or None, got {max_shapes}")
    # keep the LARGEST shapes (_SHAPES is ordered ascending): a one-shape
    # gate must exercise the shape where miscompiles hide, not the cheap one
    shapes = _SHAPES if max_shapes is None else _SHAPES[-max_shapes:]
    n_row = 1
    if mesh is not None:
        from ..parallel.mesh import ROW_AXIS

        n_row = mesh.shape.get(ROW_AXIS, 1)
        bad = [n for _, n, _ in shapes if n % max(1, n_row)]
        if bad:
            raise ValueError(
                f"selftest node counts {bad} are not divisible by the "
                f"mesh's {n_row} row shards — use n_row_shards dividing "
                f"{[n for _, n, _ in shapes]}"
            )
    obs_dev_max, null_dev_max = 0.0, 0.0
    for sizes, n, s in shapes:
        rng = np.random.default_rng(seed)

        def build():
            x = rng.standard_normal((s, n)).astype(np.float32)
            c = np.corrcoef(x, rowvar=False).astype(np.float32)
            np.fill_diagonal(c, 1.0)
            return x, c, (np.abs(c) ** 2).astype(np.float32)

        (d_data, d_corr, d_net), (t_data, t_corr, t_net) = build(), build()
        specs, pos = [], 0
        for k, sz in enumerate(sizes):
            idx = np.arange(pos, pos + sz, dtype=np.int32)
            specs.append(ModuleSpec(str(k + 1), idx, idx))
            pos += sz
        pool = np.arange(n, dtype=np.int32)

        cfg_kw = {}
        if mesh is not None:
            cfg_kw["matrix_sharding"] = "row" if n_row > 1 else "replicated"
        # chunk_size needs no mesh adjustment: the engine's
        # effective_chunk() already rounds it onto the mesh's perm axis
        eng = PermutationEngine(
            d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
            config=EngineConfig(chunk_size=16, summary_method="eigh",
                                **cfg_kw),
            mesh=mesh,
        )
        shape_tag = f"shape (n={n}, modules={sizes})"

        def _oracle_stats(idx_per_module):
            return oracle.module_stats_for_indices(
                d_corr, d_net, d_data, t_corr, t_net, t_data,
                [spec.disc_idx for spec in specs], idx_per_module,
            )

        # 1) observed pass vs oracle. These toy problems always have data,
        # so every statistic is defined: any non-finite observed entry is
        # device breakage (nanmax would silently skip it — review-caught
        # hole)
        obs = np.asarray(eng.observed())
        want_obs = _oracle_stats([spec.test_idx for spec in specs])
        if not np.isfinite(obs).all():
            raise RuntimeError(
                f"selftest FAILED on {device} at {shape_tag}: observed "
                "statistics contain non-finite values"
            )
        obs_dev = float(np.max(np.abs(obs - want_obs)))
        if not (obs_dev < atol):
            raise RuntimeError(
                f"selftest FAILED on {device} at {shape_tag}: observed "
                f"statistics deviate from the NumPy oracle by {obs_dev:.3g} "
                f"(tolerance {atol} on backend '{backend}') — the device "
                "is not computing what the host computes"
            )

        # 2) permutation null: finite, and one permutation reconstructed
        #    from the seeding contract matches the oracle end-to-end
        nulls, done = eng.run_null(n_perm, key=seed)
        nulls = np.asarray(nulls)
        if done != n_perm or not np.isfinite(nulls).all():
            raise RuntimeError(
                f"selftest FAILED on {device} at {shape_tag}: null "
                f"incomplete or non-finite ({done}/{n_perm} permutations)"
            )
        p_check = min(3, n_perm - 1)
        keys = eng.perm_keys(jax.random.key(seed), 0, n_perm)
        perm = np.asarray(
            jax.random.permutation(keys[p_check], jnp.asarray(pool))
        )
        off, idxs = 0, []
        for sz in sizes:
            idxs.append(perm[off: off + sz])
            off += sz
        # np.max, not nanmax: the device side is isfinite-checked above,
        # and a NaN in the oracle reconstruction (degenerate toy — should
        # be impossible) propagates to a failing comparison instead of
        # being silently skipped
        null_dev = float(np.max(np.abs(nulls[p_check] - _oracle_stats(idxs))))
        if not (null_dev < atol):
            raise RuntimeError(
                f"selftest FAILED on {device} at {shape_tag}: permutation "
                f"{p_check} of the null deviates from the oracle "
                f"reconstruction by {null_dev:.3g} (tolerance {atol} on "
                f"backend '{backend}') — draw/gather/statistics disagree "
                "between device and host"
            )

        # 3) streaming tallies (store_nulls=False): the superchunk
        #    executor's on-device exceedance counts must equal the
        #    materialized null's counts BIT-FOR-BIT on this backend — both
        #    run the same device arithmetic, so the comparison is exact
        #    even where MXU bf16 truncation loosens the oracle tolerance
        #    above (this is the truncating-backend half of the ISSUE-2
        #    streaming-parity acceptance criterion)
        from ..ops import pvalues as pv

        sc = eng.run_null_streaming(n_perm, obs, key=seed)
        s_hi, s_lo, s_eff = pv.tail_counts(obs, nulls[:done])
        if (sc.completed != done or (sc.hi != s_hi).any()
                or (sc.lo != s_lo).any() or (sc.eff != s_eff).any()):
            bad = int(
                (sc.hi != s_hi).sum() + (sc.lo != s_lo).sum()
                + (sc.eff != s_eff).sum()
            )
            raise RuntimeError(
                f"selftest FAILED on {device} at {shape_tag}: streaming "
                f"(store_nulls=False) exceedance tallies disagree with the "
                f"materialized null in {bad} cell(s) — the scan-fused "
                "superchunk dispatch is not computing the chunk loop's "
                "statistics"
            )
        obs_dev_max = max(obs_dev_max, obs_dev)
        null_dev_max = max(null_dev_max, null_dev)

    # 4) fused-statistics mega-kernel (ISSUE 8, stat_mode='fused'): the
    #    Pallas gather+stats+tally kernel must agree with the XLA
    #    composition on this device — values within the backend tolerance
    #    (the kernel's one-hot selection carries the same MXU rounding
    #    class as the mxu/fused gathers), and its streaming tallies must
    #    equal tail_counts of its own materialized null BIT-FOR-BIT (both
    #    outputs come from the same in-kernel registers). A kernel that
    #    fails to COMPILE here (Mosaic refusal on a new backend) is
    #    reported, not raised — the device's arithmetic is already proven
    #    by steps 1–3, and the watcher's decision grid owns the
    #    fused-step retirement policy; wrong NUMBERS still fail loudly.
    fused_stats_note = "ok"
    try:
        sizes, n, s = shapes[-1]
        rng = np.random.default_rng(seed + 1)
        x = rng.standard_normal((s, n)).astype(np.float32)
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
        np.fill_diagonal(c, 1.0)
        net = (np.abs(c) ** 2).astype(np.float32)
        specs, pos = [], 0
        for k, sz in enumerate(sizes):
            idx = np.arange(pos, pos + sz, dtype=np.int32)
            specs.append(ModuleSpec(str(k + 1), idx, idx))
            pos += sz
        pool = np.arange(n, dtype=np.int32)

        def _build(mode):
            return PermutationEngine(
                c, net, x, c, net, x, specs, pool,
                config=EngineConfig(chunk_size=16, summary_method="power",
                                    power_iters=30, superchunk=2,
                                    autotune=False, stat_mode=mode),
            )

        e_f = _build("fused")
        obs_f = np.asarray(e_f.observed())
        nulls_f, done_f = e_f.run_null(n_perm, key=seed)
        nulls_x, _ = _build("xla").run_null(n_perm, key=seed)
        fdev = float(np.nanmax(np.abs(
            np.asarray(nulls_f) - np.asarray(nulls_x)
        )))
        if not (fdev < atol):
            raise RuntimeError(
                f"selftest FAILED on {device}: fused-statistics kernel "
                f"(stat_mode='fused') deviates from the XLA composition "
                f"by {fdev:.3g} (tolerance {atol} on backend "
                f"'{backend}') — the mega-kernel is not computing the "
                "engine's statistics"
            )
        from ..ops import pvalues as pv

        sc_f = e_f.run_null_streaming(n_perm, obs_f, key=seed)
        f_hi, f_lo, f_eff = pv.tail_counts(
            obs_f, np.asarray(nulls_f)[:done_f]
        )
        if ((sc_f.hi != f_hi).any() or (sc_f.lo != f_lo).any()
                or (sc_f.eff != f_eff).any()):
            raise RuntimeError(
                f"selftest FAILED on {device}: fused-statistics streaming "
                "tallies disagree with the kernel's own materialized null "
                "— the in-VMEM tally fold is not counting the statistics "
                "it computed"
            )
    except RuntimeError:
        raise
    # netrep: allow(exception-taxonomy) — compile-refusal split (PR 8): unavailable kernel is REPORTED in the summary; wrong numbers raise above
    except Exception as e:  # kernel unavailable on this backend
        fused_stats_note = f"skipped ({type(e).__name__}: {e})"

    out = {
        "ok": True,
        "device": device,
        "backend": backend,
        "mesh": None if mesh is None else dict(mesh.shape),
        "n_perm": int(n_perm),
        "n_shapes": len(shapes),
        "shape_nodes": [n for _, n, _ in shapes],
        "atol": atol,
        "observed_max_abs_dev": obs_dev_max,
        "null_reconstruction_max_abs_dev": null_dev_max,
        "streaming_counts_exact": True,  # raised above otherwise
        "fused_stats": fused_stats_note,
        "elapsed_s": round(time.perf_counter() - t_start, 2),
    }
    if verbose:
        print(
            f"netrep_tpu selftest OK on {device}: observed dev "
            f"{obs_dev_max:.2e}, null-reconstruction dev {null_dev_max:.2e} "
            f"across {len(shapes)} shapes (atol {atol}), "
            f"{n_perm} perms in {out['elapsed_s']}s"
        )
    return out
