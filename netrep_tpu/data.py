"""Bundled example data — the rebuild of the reference's packaged toy
dataset (SURVEY.md §2.1 "Example data": `discovery_data`,
`discovery_correlation`, `discovery_network`, `module_labels`, `test_data`,
`test_correlation`, `test_network`; ~100 nodes, 4 modules — the vignette /
integration-test fixture, BASELINE.json:7 "Config A").

The reference ships serialized `.rda` matrices; shipping binary blobs in a
source tree buys nothing here, so the equivalent fixture is *generated*
deterministically: :func:`load_example` always returns the same matrices for
the same arguments (seeded PRNG), which is exactly the property the bundled
data provides — a stable, documented fixture for docs, tests, and benchmarks.

The construction plants correlated modules shared by discovery and test
datasets with partial node overlap, shuffled test-node order, per-node signs
and noise levels that are deterministic functions of the node *name* (hence
consistent across datasets) — giving each module a heterogeneous, preserved
degree structure so all seven statistics carry signal.
"""

from __future__ import annotations

import zlib

import numpy as np

__all__ = [
    "make_example_pair", "load_example", "make_mixed_pair", "pair_frames",
]


def make_example_pair(
    rng: np.random.Generator,
    n_disc: int = 90,
    n_test: int = 80,
    n_overlap: int = 70,
    n_samples_disc: int = 40,
    n_samples_test: int = 35,
    module_sizes: tuple[int, ...] = (15, 12, 10, 8),
    noise: float = 0.7,
    beta: float = 2.0,
) -> dict:
    """Synthetic discovery/test co-expression pair with planted modules.

    Parameters
    ----------
    rng : numpy Generator driving every random draw.
    n_disc, n_test : node counts of the discovery / test datasets.
    n_overlap : number of discovery nodes also present in the test dataset
        (test nodes appear in shuffled order, so name-based alignment is
        exercised).
    n_samples_disc, n_samples_test : sample counts of the data matrices.
    module_sizes : planted module sizes (labels "1", "2", ...; remaining
        discovery nodes are background "0").
    noise : per-node noise level multiplier (lower = tighter modules).
    beta : soft-threshold power for the adjacency (`|corr| ** beta`).

    Returns
    -------
    dict with keys ``discovery`` / ``test`` (each ``{data, correlation,
    network, names}``), ``labels`` ({node_name: module_label}), and
    ``module_sizes`` ({label: size}).
    """
    if sum(module_sizes) > n_disc:
        raise ValueError(
            f"sum(module_sizes)={sum(module_sizes)} exceeds n_disc={n_disc}; "
            "planted modules must fit in the discovery dataset"
        )
    if not (0 <= n_overlap <= min(n_disc, n_test)):
        raise ValueError(
            f"n_overlap={n_overlap} must be between 0 and "
            f"min(n_disc, n_test)={min(n_disc, n_test)}"
        )
    names_disc = [f"g{i:04d}" for i in range(n_disc)]
    extra = [f"t{i:04d}" for i in range(n_test - n_overlap)]
    names_test = list(rng.permutation(names_disc[:n_overlap] + extra))

    labels = np.zeros(n_disc, dtype=object)
    pos = 0
    latents = {}
    for k, sz in enumerate(module_sizes, start=1):
        labels[pos: pos + sz] = str(k)
        latents[str(k)] = (
            rng.standard_normal(n_samples_disc),
            rng.standard_normal(n_samples_test),
        )
        pos += sz
    labels[pos:] = "0"

    n_planted = int(sum(module_sizes))

    def build(names, n_samples, which):
        x = rng.standard_normal((n_samples, len(names)))
        for j, nm in enumerate(names):
            if nm in names_disc[:n_planted]:
                k = labels[names_disc.index(nm)]
                if k != "0":
                    # per-node sign and noise level are deterministic in the
                    # node name, hence consistent across datasets — gives the
                    # module a heterogeneous, *preserved* degree structure
                    # (cor.degree has no signal in equal-SNR toy data).
                    sgn = 1.0 if zlib.crc32(nm.encode()) % 3 else -1.0
                    lvl = 0.35 + 1.3 * ((zlib.crc32(nm.encode()[::-1]) % 97) / 97)
                    x[:, j] = sgn * latents[k][which] + lvl * noise * x[:, j]
        corr = np.corrcoef(x, rowvar=False)
        net = np.abs(corr) ** beta
        np.fill_diagonal(net, 1.0)
        return x, corr, net

    d_data, d_corr, d_net = build(names_disc, n_samples_disc, 0)
    t_data, t_corr, t_net = build(names_test, n_samples_test, 1)

    return dict(
        discovery=dict(data=d_data, correlation=d_corr, network=d_net, names=names_disc),
        test=dict(data=t_data, correlation=t_corr, network=t_net, names=names_test),
        labels={nm: str(l) for nm, l in zip(names_disc, labels)},
        module_sizes={
            str(k): sz for k, sz in enumerate(module_sizes, start=1)
        },
    )


def make_mixed_pair(
    n_genes: int,
    n_modules: int,
    n_samples: int = 40,
    module_size: tuple[int, int] = (16, 28),
    preserved_fraction: float = 0.5,
    strength: tuple[float, float] = (0.6, 2.2),
    seed: int = 0,
) -> dict:
    """Mixed preserved/random fixture for the adaptive (sequential
    early-stopping) engine: the first ``preserved_fraction`` of the planted
    modules replicate in the test dataset, the rest are noise there.

    Each module is a single latent factor with *heterogeneous per-node
    loadings* drawn once and reused in the test dataset for preserved
    modules — equal loadings would leave the within-module correlation
    pattern flat and ``cor.cor``/``cor.degree`` without signal, making even
    genuinely preserved modules look borderline. Preserved modules come out
    significant on every statistic; random modules on none — the
    clean separation the sequential stopping rules retire fastest on, and
    the decision-agreement oracle tests and ``bench.py --config adaptive``
    both need.

    Returns ``{discovery, test, specs, pool}`` where ``discovery``/``test``
    are ``(data, correlation, network)`` float32 triples, ``specs`` is the
    aligned ``(label, indices)`` module list (labels "1", "2", ... in
    planted order: preserved first), and ``pool`` is the full node range.
    """
    rng = np.random.default_rng(seed)
    sizes = rng.integers(module_size[0], module_size[1] + 1, size=n_modules)
    if int(sizes.sum()) > n_genes:
        raise ValueError(
            f"planted modules ({int(sizes.sum())} nodes) exceed "
            f"n_genes={n_genes}"
        )
    n_preserved = int(round(preserved_fraction * n_modules))
    xd = rng.standard_normal((n_samples, n_genes))
    xt = rng.standard_normal((n_samples, n_genes))
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        load = rng.uniform(*strength, size=int(sz))
        xd[:, pos: pos + sz] += rng.standard_normal((n_samples, 1)) * load
        if k < n_preserved:
            xt[:, pos: pos + sz] += rng.standard_normal((n_samples, 1)) * load
        specs.append((str(k + 1), np.arange(pos, pos + sz, dtype=np.int32)))
        pos += sz

    def mats(x):
        corr = np.corrcoef(x, rowvar=False)
        np.fill_diagonal(corr, 1.0)
        return (
            x.astype(np.float32),
            corr.astype(np.float32),
            (np.abs(corr) ** 2).astype(np.float32),
        )

    return dict(
        discovery=mats(xd),
        test=mats(xt),
        specs=specs,
        pool=np.arange(n_genes, dtype=np.int32),
        n_preserved=n_preserved,
    )


def pair_frames(pair: dict) -> tuple[dict, dict]:
    """Package a :func:`make_example_pair` result as the pandas inputs
    (named nodes) ``module_preservation`` takes — the one shared copy of
    this transform for tests, docs, and notebooks. Lives here (not in a
    test conftest) so imports are path-stable under any pytest import mode.
    """
    import pandas as pd

    def mk(ds):
        names = ds["names"]
        return dict(
            data=pd.DataFrame(ds["data"], columns=names),
            correlation=pd.DataFrame(ds["correlation"], index=names,
                                     columns=names),
            network=pd.DataFrame(ds["network"], index=names, columns=names),
        )

    return mk(pair["discovery"]), mk(pair["test"])


def load_example(seed: int = 42) -> dict:
    """The framework's stable example fixture, shaped like the reference's
    bundled data objects (SURVEY.md §2.1): a dict with
    ``discovery_data``, ``discovery_correlation``, ``discovery_network``,
    ``module_labels``, ``test_data``, ``test_correlation``, ``test_network``,
    plus ``discovery_names`` / ``test_names`` (node labels, since numpy
    arrays don't carry dimnames the way R matrices do).

    Matrices are plain float64 ndarrays; ``module_labels`` maps discovery
    node name → module label ("0" = background). Deterministic in ``seed``.

    Feed it straight to the API::

        ex = load_example()
        import pandas as pd
        res = netrep_tpu.module_preservation(
            network={"discovery": pd.DataFrame(ex["discovery_network"],
                                               index=ex["discovery_names"],
                                               columns=ex["discovery_names"]),
                     "test": ...},
            ...)

    or use the name lists with the dict-of-DataFrames pattern shown in the
    vignette (docs/vignette.md).
    """
    pair = make_example_pair(np.random.default_rng(seed))
    return {
        "discovery_data": pair["discovery"]["data"],
        "discovery_correlation": pair["discovery"]["correlation"],
        "discovery_network": pair["discovery"]["network"],
        "test_data": pair["test"]["data"],
        "test_correlation": pair["test"]["correlation"],
        "test_network": pair["test"]["network"],
        "module_labels": pair["labels"],
        "discovery_names": pair["discovery"]["names"],
        "test_names": pair["test"]["names"],
    }
