"""Exact permutation p-values and permutation-count planning.

Reimplements the reference's p-value layer (SURVEY.md §2.1 "p-value
aggregation"): the reference feeds null-distribution exceedance counts to
``statmod::permp`` — the Phipson & Smyth (2010, *Permutation p-values should
never be zero*) estimator that accounts for the finite permutation space when
permutations are drawn at random (with replacement) — honoring
``alternative = "greater" / "less" / "two.sided"``. SURVEY.md §7 lists exact
reproduction of this math as a hard requirement ("it's the user-visible
number").

Also provides :func:`required_perms` (SURVEY.md §3.4): the smallest number of
permutations whose minimum achievable p-value clears a significance threshold
after Bonferroni adjustment.
"""

from __future__ import annotations

import math

import numpy as np
from scipy import integrate as _integrate
from scipy import stats as _sstats

#: Above this many total possible permutations, permp switches from the exact
#: finite sum to the integral approximation (mirrors statmod's auto rule).
_EXACT_LIMIT = 10_000


def permp(
    x: np.ndarray,
    nperm: int,
    total_nperm: float | None = None,
    method: str = "auto",
) -> np.ndarray:
    """Phipson–Smyth exact permutation p-value.

    Parameters
    ----------
    x : array of exceedance counts — the number of null statistics at least
        as extreme as the observed statistic.
    nperm : number of random permutations actually drawn.
    total_nperm : size of the full permutation space (may be ``None``/inf for
        effectively infinite spaces).
    method : ``'exact'`` — average the binomial CDF over the attainable true
        p-values ``v/total_nperm``; ``'approximate'`` — the integral-corrected
        ``(x+1)/(nperm+1)``; ``'auto'`` — exact when the space is small.

    Notes
    -----
    With ``B ~ Binomial(nperm, p_true)`` and ``p_true`` uniform on
    ``{1/mt, ..., mt/mt}``, the exact estimator is
    ``mean_v P(B <= x | p_true = v/mt)``. Its large-``mt`` limit is
    ``(x+1)/(nperm+1)`` because ``∫_0^1 F(x; n, u) du = (x+1)/(n+1)``; the
    approximate method subtracts the midpoint-rule boundary correction
    ``∫_0^{1/(2 mt)} F(x; n, u) du``.

    Fidelity vs ``statmod::permp`` (re-verification debt, SURVEY.md §7
    "Exact p-values"; the reference mount is empty and no R is installed, so
    statmod itself cannot be executed here):

    - The *exact* method is the estimator as published (Phipson & Smyth
      2010, eq. 2) — ``tests/test_pvalues.py`` pins it against an
      independent exact-rational-arithmetic oracle, so any disagreement
      with statmod could only come from statmod deviating from its own
      paper.
    - The *approximate* method evaluates the same boundary-correction
      integral statmod computes (statmod uses 128-point Gauss–Legendre;
      here adaptive quadrature — agreement to quadrature tolerance,
      ~1e-10, far below the estimator's own Monte-Carlo error).
    - The ``'auto'`` rule (exact iff ``total_nperm <= 10_000``) mirrors
      statmod's documented switch; flagged for re-verification against the
      source if a reference mount ever appears.
    """
    x = np.atleast_1d(np.asarray(x, dtype=np.float64))
    x = np.clip(x, 0, nperm)
    biased = (x + 1.0) / (nperm + 1.0)

    if total_nperm is None or not np.isfinite(total_nperm):
        return biased

    mt = float(total_nperm)
    if method == "auto":
        method = "exact" if mt <= _EXACT_LIMIT else "approximate"

    if method == "exact":
        probs = np.arange(1, int(mt) + 1, dtype=np.float64) / mt
        return _sstats.binom.cdf(x[:, None], nperm, probs[None, :]).mean(axis=1)
    if method == "approximate":
        out = np.empty_like(biased)
        for i, xi in enumerate(x):
            corr, _err = _integrate.quad(
                lambda u: _sstats.binom.cdf(xi, nperm, u), 0.0, 0.5 / mt
            )
            out[i] = biased[i] - corr
        return np.clip(out, 1.0 / mt if mt > 0 else 0.0, 1.0)
    raise ValueError(f"unknown permp method: {method!r}")


def exceedance_counts(
    observed: np.ndarray,
    nulls: np.ndarray,
    alternative: str = "greater",
) -> tuple[np.ndarray, np.ndarray]:
    """Count null draws at least as extreme as the observed value.

    Parameters
    ----------
    observed : (...,) observed statistics.
    nulls : (nperm, ...) null draws (NaN entries are ignored and excluded
        from the effective permutation count).
    alternative : 'greater' | 'less' | 'two.sided'.

    Returns
    -------
    (counts, effective_nperm) — for ``two.sided`` the counts are returned for
    both tails as the *minimum* tail count; callers double the resulting
    p-value (capped at 1), matching the standard two-sided permutation rule.

    Convention note (documented deviation candidate, SURVEY.md §7): the
    reference's R layer was not observable (empty mount), so its two-sided
    rule could not be read. ``min-tail × 2, capped at 1`` is the standard
    permutation convention and is what this layer implements; statmod's own
    ``twosided=`` flag instead expects callers to count exceedances of
    ``|statistic|``, which is only equivalent for symmetric nulls. If the
    reference is ever re-verified to use the |statistic| convention, change
    ONLY this function.
    """
    valid = ~np.isnan(nulls)
    eff = valid.sum(axis=0)
    if alternative == "greater":
        cnt = np.nansum(nulls >= observed[None], axis=0)
    elif alternative == "less":
        cnt = np.nansum(nulls <= observed[None], axis=0)
    elif alternative == "two.sided":
        hi = np.nansum(nulls >= observed[None], axis=0)
        lo = np.nansum(nulls <= observed[None], axis=0)
        cnt = np.minimum(hi, lo)
    else:
        raise ValueError(f"unknown alternative: {alternative!r}")
    return cnt, eff


def _grouped_permp(counts, eff, total_nperm) -> np.ndarray:
    """Vectorized :func:`permp` over a (counts, effective-nperm) cell grid:
    cells are grouped by effective permutation count (usually one group —
    NaN-free nulls) instead of calling per cell. Zero-draw cells stay NaN.
    Shared by the null-array and streamed-counts p-value paths so the
    estimator cannot drift between them."""
    flat_c = np.asarray(counts, dtype=np.float64).reshape(-1)
    flat_n = np.asarray(eff, dtype=np.int64).reshape(-1)
    p = np.full(flat_c.shape, np.nan)
    for n in np.unique(flat_n):
        sel = flat_n == n
        if n > 0:
            p[sel] = permp(flat_c[sel], int(n), total_nperm)
    return p.reshape(np.asarray(counts).shape)


def permutation_pvalues(
    observed: np.ndarray,
    nulls: np.ndarray,
    alternative: str = "greater",
    total_nperm: float | None = None,
) -> np.ndarray:
    """Per-statistic permutation p-values from observed values and the null
    array — the reference's post-null R-side aggregation (SURVEY.md §3.1).

    NaN observed statistics (e.g. data-less variant) yield NaN p-values.
    """
    observed = np.asarray(observed, dtype=np.float64)
    counts, eff = exceedance_counts(observed, nulls, alternative)
    p = _grouped_permp(counts, eff, total_nperm)
    if alternative == "two.sided":
        p = np.minimum(2.0 * p, 1.0)
    p[np.isnan(observed)] = np.nan
    return p


def tail_counts(
    observed: np.ndarray, nulls: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Both-tail exceedance tallies + per-cell valid draw counts of a
    materialized null array — the lift from null space into the streaming
    executor's count space (``(hi, lo, eff)``, each shaped like one null
    row). Lets :func:`netrep_tpu.models.results.combine_analyses` pool a
    materialized result with count-only (``store_nulls=False``) results,
    and pins streaming/materialized parity in tests: a streamed run's
    device tallies must equal this function applied to the same key's
    materialized null."""
    observed = np.asarray(observed, dtype=np.float64)
    nulls = np.asarray(nulls)
    with np.errstate(invalid="ignore"):
        hi = (nulls >= observed[None]).sum(axis=0)
        lo = (nulls <= observed[None]).sum(axis=0)
    eff = (~np.isnan(nulls)).sum(axis=0)
    return (hi.astype(np.int64), lo.astype(np.int64), eff.astype(np.int64))


def counts_pvalues(
    observed: np.ndarray,
    hi: np.ndarray,
    lo: np.ndarray,
    eff: np.ndarray,
    alternative: str = "greater",
    total_nperm: float | None = None,
) -> np.ndarray:
    """Exact Phipson–Smyth p-values straight from streamed exceedance
    tallies (``store_nulls=False``): ``hi``/``lo`` are the per-(module,
    statistic) counts of null draws at least / at most as extreme as the
    observed value and ``eff`` the per-cell valid (non-NaN) draw counts —
    exactly what :func:`tail_counts` computes from a materialized null, so
    the two result modes produce identical p-values for identical counts
    (the estimator itself is the shared :func:`_grouped_permp`). The tail
    convention matches :func:`exceedance_counts` (two-sided: min tail,
    doubled, capped at 1); NaN observed statistics yield NaN p-values."""
    observed = np.asarray(observed, dtype=np.float64)
    hi = np.asarray(hi)
    lo = np.asarray(lo)
    if alternative == "greater":
        cnt = hi
    elif alternative == "less":
        cnt = lo
    elif alternative == "two.sided":
        cnt = np.minimum(hi, lo)
    else:
        raise ValueError(f"unknown alternative: {alternative!r}")
    p = _grouped_permp(cnt, eff, total_nperm)
    if alternative == "two.sided":
        p = np.minimum(2.0 * p, 1.0)
    p[np.isnan(observed)] = np.nan
    return p


def effective_nperm(nulls: np.ndarray) -> np.ndarray:
    """Per-module permutation counts actually present in a null array —
    rows where *any* statistic is finite count (an adaptive run NaNs the
    whole (module, :) row past retirement; a data-less run NaNs only the
    data statistics, which must still count as drawn permutations).

    ``nulls`` is ``(nperm, n_modules, n_stats)``; returns ``(n_modules,)``.
    """
    return np.asarray(
        (~np.isnan(nulls)).any(axis=-1).sum(axis=0), dtype=np.int64
    )


def sequential_pvalues(
    observed: np.ndarray,
    nulls: np.ndarray,
    alternative: str = "greater",
    total_nperm: float | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Sequential (early-stopped) permutation p-values — the estimator the
    adaptive engine's nulls are read with (``p_type='sequential'``).

    The adaptive loop (Besag & Clifford 1991 stopping,
    :mod:`netrep_tpu.ops.sequential`) retires each module at its own
    permutation count and leaves the module's null rows NaN past
    retirement. Because retirement happens only at chunk boundaries on
    tallied counts, the per-module estimator is exactly Phipson–Smyth at
    the module's ``n_used`` — :func:`permutation_pvalues` already groups
    cells by effective permutation count, so this composes with the exact-p
    machinery unchanged; what this wrapper adds is the per-module
    ``n_perm_used`` bookkeeping the results layer records.

    Returns ``(p_values, n_perm_used)`` with ``n_perm_used`` of shape
    ``(n_modules,)``.
    """
    nulls = np.asarray(nulls)
    return (
        permutation_pvalues(observed, nulls, alternative, total_nperm),
        effective_nperm(nulls),
    )


# --- Generalized-Pareto tail sharpening (Knijnenburg et al. 2009) ----------

#: Number of top-order statistics the GPD tail fit starts from — the
#: standard 250-exceedance rule of Knijnenburg et al. 2009 ("Fewer
#: permutations, more accurate P-values", §Methods).
_GPD_START_EXCEED = 250
#: Step the exceedance count is reduced by each time the A–D gate rejects.
_GPD_STEP = 10
#: Floor below which the fit is abandoned as untrustworthy.
_GPD_MIN_EXCEED = 30
#: With at least this many null draws beyond the observed value the exact
#: Phipson–Smyth estimator already resolves the cell; the tail fit is
#: reserved for the far tail it cannot reach (Knijnenburg's x < 10 rule).
_GPD_ECDF_COUNT = 10

# Choulakian & Stephens (2001, "Goodness-of-fit tests for the generalized
# Pareto distribution") case-3 upper-tail critical points of the
# Anderson–Darling A² at α = 0.05, both parameters estimated, indexed by
# the GPD shape ξ (= -k in their parametrization). Linearly interpolated
# in ξ and clamped at the table ends. The gate is a coarse accept/refuse
# screen for extrapolation safety, not a calibrated hypothesis test.
_AD_XI = np.array([-0.9, -0.5, -0.2, -0.1, 0.0, 0.1, 0.2, 0.3, 0.4, 0.5])
_AD_CRIT = np.array(
    [0.771, 0.830, 0.903, 0.935, 0.974, 1.020, 1.074, 1.140, 1.221, 1.321]
)


def _gpd_ad_stat(exc: np.ndarray, xi: float, scale: float) -> float:
    """Anderson–Darling A² of exceedances against a fitted GPD(ξ, σ)."""
    z = _sstats.genpareto.cdf(np.sort(exc), xi, loc=0.0, scale=scale)
    z = np.clip(z, 1e-12, 1.0 - 1e-12)
    n = z.size
    i = np.arange(1, n + 1, dtype=np.float64)
    return float(
        -n - np.mean((2.0 * i - 1.0) * (np.log(z) + np.log1p(-z[::-1])))
    )


def _gpd_cell(y: np.ndarray, obs: float) -> tuple[float, bool]:
    """GPD tail p-value for one cell: ``y`` ascending-sorted valid null
    draws, ``obs`` the observed statistic (upper tail). Returns
    ``(p_tail, tail_ok)`` — NaN/False whenever the exact estimator is
    already adequate, the observed value is not in the fitted tail, or the
    Anderson–Darling gate refuses every candidate fit."""
    n = y.size
    if n < 2 * _GPD_MIN_EXCEED or not np.isfinite(obs):
        return np.nan, False
    if int((y >= obs).sum()) >= _GPD_ECDF_COUNT:
        return np.nan, False
    n_exc = min(_GPD_START_EXCEED, n // 4)
    while n_exc >= _GPD_MIN_EXCEED:
        t = 0.5 * (y[n - n_exc - 1] + y[n - n_exc])
        exc = y[n - n_exc:] - t
        if obs > t and exc[-1] > 0.0:
            try:
                xi, _loc, scale = _sstats.genpareto.fit(exc, floc=0.0)
            # netrep: allow(exception-taxonomy) — MLE on a pathological tail may fail inside scipy; a failed fit only rejects this threshold candidate (the search steps down, p_tail stays NaN), never a wrong p-value
            except Exception:
                xi, scale = np.nan, 0.0
            if np.isfinite(xi) and np.isfinite(scale) and scale > 0.0:
                a2 = _gpd_ad_stat(exc, xi, scale)
                if np.isfinite(a2) and a2 <= float(
                    np.interp(xi, _AD_XI, _AD_CRIT)
                ):
                    sf = float(
                        _sstats.genpareto.sf(obs - t, xi, loc=0.0, scale=scale)
                    )
                    return (n_exc / n) * sf, True
        n_exc -= _GPD_STEP
    return np.nan, False


def gpd_tail_pvalues(
    observed: np.ndarray,
    nulls: np.ndarray,
    alternative: str = "greater",
    nulls_exact: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Generalized-Pareto tail p-values (Knijnenburg et al. 2009) beside the
    exact permutation estimator.

    For cells whose observed statistic lands beyond (nearly) every null draw
    the exact Phipson–Smyth p saturates at ~1/(nperm+1); fitting a GPD to
    the null tail (threshold at the 250th largest draw, reduced by 10 while
    an Anderson–Darling goodness-of-fit gate rejects) extrapolates far
    smaller p-values from the same draws:
    ``p_tail = (n_exc / n) * SF_GPD(obs - t)``.

    Parameters
    ----------
    observed : (...,) observed statistics.
    nulls : (nperm, ...) null draws (NaN entries ignored, as in
        :func:`exceedance_counts`).
    alternative : 'greater' | 'less' | 'two.sided' (min tail doubled,
        capped at 1 — the convention of :func:`permutation_pvalues`).
    nulls_exact : pass False when the null VALUES came through the bf16
        screened fast-pass (ISSUE 16: decided permutations keep their
        bf16-rounded statistics). The call then refuses: the GPD fit
        reads the extreme draws themselves, and bf16 quantization
        (8-bit significand) collapses the tail onto a handful of
        plateaus — the threshold excess distribution degenerates and the
        Anderson–Darling gate no longer measures what it gates. Exact
        counts-based p-values are unaffected; rerun with
        ``null_precision='f32'`` for a tail-fittable null array.

    Returns
    -------
    ``(p_tail, tail_ok)`` shaped like ``observed``. ``tail_ok`` is True
    only where a gated fit produced the value; everywhere else ``p_tail``
    is NaN — callers must fall back to the exact estimator there. The fit
    is only attempted where fewer than 10 null draws reach the observed
    value (the exact estimator already resolves denser cells).
    """
    if not nulls_exact:
        raise ValueError(
            "gpd_tail_pvalues refuses bf16-screened null values "
            "(nulls_exact=False): the screened fast-pass stores decided "
            "permutations' bf16-rounded statistics, whose quantized tail "
            "plateaus break the GPD threshold-excess fit. The exact "
            "p_values (exceedance counts) are unaffected — use them, or "
            "rerun with EngineConfig(null_precision='f32') to materialize "
            "a tail-fittable f32 null array"
        )
    observed = np.asarray(observed, dtype=np.float64)
    nulls = np.asarray(nulls, dtype=np.float64)
    if alternative not in ("greater", "less", "two.sided"):
        raise ValueError(f"unknown alternative: {alternative!r}")
    flat_obs = observed.reshape(-1)
    flat_null = nulls.reshape(nulls.shape[0], -1)
    p = np.full(flat_obs.shape, np.nan)
    ok = np.zeros(flat_obs.shape, dtype=bool)
    for j in range(flat_obs.size):
        o = flat_obs[j]
        if not np.isfinite(o):
            continue
        col = flat_null[:, j]
        col = np.sort(col[~np.isnan(col)])
        if col.size == 0:
            continue
        if alternative == "greater":
            p[j], ok[j] = _gpd_cell(col, o)
        elif alternative == "less":
            p[j], ok[j] = _gpd_cell(np.sort(-col), -o)
        else:  # two.sided: fit the minority tail, double, cap at 1
            if int((col >= o).sum()) <= int((col <= o).sum()):
                pj, okj = _gpd_cell(col, o)
            else:
                pj, okj = _gpd_cell(np.sort(-col), -o)
            p[j], ok[j] = (min(2.0 * pj, 1.0) if okj else np.nan), okj
    return p.reshape(observed.shape), ok.reshape(observed.shape)


def log_total_permutations(pool_size: int, module_sizes) -> float:
    """Natural log of the number of *ordered* disjoint node-set assignments —
    the size of the permutation space sampled by the engine: the falling
    factorial ``pool! / (pool - Σm)!`` (node order within a module matters
    because statistics pair nodes positionally with discovery properties)."""
    take = int(np.sum(module_sizes))
    if take > pool_size:
        return float("inf")
    return float(
        math.lgamma(pool_size + 1) - math.lgamma(pool_size - take + 1)
    )


def total_permutations(pool_size: int, module_sizes) -> float:
    """Size of the permutation space (inf if it overflows float range)."""
    lg = log_total_permutations(pool_size, module_sizes)
    return math.exp(lg) if lg < 700 else float("inf")


def required_perms(alpha: float = 0.05, n_tests: int = 1, alternative: str = "greater") -> int:
    """Smallest number of permutations whose minimum achievable p-value
    (``1/(nperm+1)``, or ``2/(nperm+1)`` two-sided) clears ``alpha`` after
    Bonferroni adjustment across ``n_tests`` module×statistic tests
    (SURVEY.md §3.4)."""
    if not 0 < alpha < 1:
        raise ValueError("alpha must be in (0, 1)")
    thresh = alpha / max(n_tests, 1)
    tails = 2.0 if alternative == "two.sided" else 1.0
    return int(math.ceil(tails / thresh)) - 1
