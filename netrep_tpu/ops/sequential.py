"""Sequential early-stopping for the permutation null (Besag & Clifford
1991, *Sequential Monte Carlo p-values*; Phipson & Smyth 2010 §4).

The fixed-``n_perm`` engine spends the same permutation budget on every
module, but most modules are statistically decided long before the budget
is exhausted: a clearly-null module racks up exceedances almost every draw
(Besag–Clifford: once ``h`` exceedances have occurred, the p-value estimate
``(c+1)/(n+1)`` has bounded relative resampling error and cannot cross a
small ``alpha`` anymore), and a clearly-preserved module's exceedance count
stays at 0 until even the top of its Clopper–Pearson interval sits below
``alpha``. :class:`StopMonitor` folds each chunk's per-(module, statistic)
exceedance counts into running tallies on the host and retires modules whose
decision at ``alpha`` is settled for every computable statistic — the engine
then re-buckets the remaining modules so later chunks genuinely shrink
(:meth:`netrep_tpu.parallel.engine.PermutationEngine.rebucket`).

Both stopping rules compose exactly with the Phipson–Smyth estimator
(:func:`netrep_tpu.ops.pvalues.permp`): a retired module's p-value is
``permp(c, n_used)`` at its per-module permutation count, which is what
:func:`netrep_tpu.ops.pvalues.permutation_pvalues` already computes from a
null array whose retired tail is NaN. Decisions are taken only at chunk
boundaries, so they are deterministic in (seed, chunk size) and
checkpoint/resume-exact (the tallies and retired set ride the checkpoint —
``utils/checkpoint.py``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

_ALTERNATIVES = ("greater", "less", "two.sided")


@dataclasses.dataclass(frozen=True)
class StopRule:
    """Stopping-rule knobs for :class:`StopMonitor`.

    Attributes
    ----------
    h : Besag–Clifford exceedance budget: a (module, statistic) cell is
        decided once its exceedance count reaches ``h`` — the sequential
        estimator ``(c+1)/(n+1)`` then has coefficient of variation
        ≲ 1/sqrt(h) and, for any ``n_used >= h/alpha``, can no longer fall
        below ``alpha``. 16 bounds the relative resampling error at ~25%,
        ample for accept/reject at alpha=0.05 (the estimate itself is ≥
        17/(n+1), decided far above alpha whenever the rule can fire).
    alpha : decision threshold the CP rule settles against (the per-test
        significance level the caller will read the p-values at).
    confidence : coverage of the Clopper–Pearson interval used by the
        "decided at alpha" rule. 0.999 keeps the per-cell risk of retiring
        on the wrong side of alpha at 1e-3 — small against the Monte-Carlo
        error a fixed-n run carries anyway.
    min_perms : never retire a module before this many permutations, so
        every module's null gets a floor sample even when the rules fire
        instantly (and so tiny-alpha CP decisions aren't made from a
        handful of draws).
    """

    h: int = 16
    alpha: float = 0.05
    confidence: float = 0.999
    min_perms: int = 128

    def __post_init__(self):
        if self.h < 1:
            raise ValueError(f"h must be >= 1, got {self.h}")
        if not 0 < self.alpha < 1:
            raise ValueError(f"alpha must be in (0, 1), got {self.alpha}")
        if not 0.5 <= self.confidence < 1:
            raise ValueError(
                f"confidence must be in [0.5, 1), got {self.confidence}"
            )
        if self.min_perms < 1:
            raise ValueError(
                f"min_perms must be >= 1, got {self.min_perms}"
            )


def _cp_bounds(c: np.ndarray, n: int, delta: float):
    """Two-sided Clopper–Pearson ``1 - delta`` interval for a binomial
    proportion with ``c`` successes of ``n`` — vectorized in ``c``."""
    from scipy import stats as _sstats

    c = np.asarray(c, dtype=np.float64)
    lo = np.where(c > 0, _sstats.beta.ppf(delta / 2, c, n - c + 1), 0.0)
    hi = np.where(c < n, _sstats.beta.ppf(1 - delta / 2, c + 1, n - c), 1.0)
    return lo, hi


class StopMonitor:
    """Host-side running tallies + retirement decisions for an adaptive
    permutation run.

    Parameters
    ----------
    observed : (n_modules, n_cells) observed statistics. Callers with extra
        axes flatten them into the cell axis (the multi-test engine folds
        its T datasets in as ``(K, T*7)``); NaN cells (data-less variant)
        are never computable and do not block retirement.
    alternative : 'greater' | 'less' | 'two.sided' — must match the tail
        convention the final p-values will use
        (:func:`netrep_tpu.ops.pvalues.exceedance_counts`). Two-sided
        tallies keep BOTH tails (min-of-sums ≠ sum-of-mins across chunks).
    rule : :class:`StopRule`.
    """

    def __init__(self, observed: np.ndarray, alternative: str, rule: StopRule):
        if alternative not in _ALTERNATIVES:
            raise ValueError(
                f"alternative must be one of {_ALTERNATIVES}, "
                f"got {alternative!r}"
            )
        self.observed = np.atleast_2d(np.asarray(observed, dtype=np.float64))
        self.alternative = alternative
        self.rule = rule
        k, s = self.observed.shape
        self.hi = np.zeros((k, s), dtype=np.int64)   # nulls >= observed
        self.lo = np.zeros((k, s), dtype=np.int64)   # nulls <= observed
        #: per-cell valid (non-NaN) draw counts — tracked only by the
        #: streaming (store_nulls=False) adaptive path, which has no null
        #: array to recover them from; None on materialized runs
        self.eff: np.ndarray | None = None
        self.n_used = np.zeros(k, dtype=np.int64)
        self.active = np.ones(k, dtype=bool)
        #: optional :class:`~netrep_tpu.utils.telemetry.Telemetry` bus the
        #: adaptive loops attach — retirement decisions are emitted HERE
        #: (the tallies live here) as one ``module_retired`` event per
        #: retired module, carrying its per-cell exceedance tallies
        self.telemetry = None
        #: total permutation indices folded so far — always a whole number
        #: of chunks. May lag the loop's `completed` counter by one chunk
        #: when an interrupt lands between the null write and the fold; the
        #: adaptive loop re-folds the gap from the null array on resume so
        #: the two can never diverge across a checkpoint.
        self.folded = 0
        self._nan_cells = np.isnan(self.observed)
        #: warm-start pseudo-counts from a PRIOR run of the same cell
        #: (:meth:`seed_priors`) — consulted ONLY by the decision rules;
        #: reported tallies/p-values stay fresh-draw-only
        self.prior_hi: np.ndarray | None = None
        self.prior_lo: np.ndarray | None = None
        self.prior_n: np.ndarray | None = None

    # -- state ------------------------------------------------------------

    @property
    def n_modules(self) -> int:
        return self.observed.shape[0]

    def active_positions(self) -> np.ndarray:
        """Global module positions still drawing permutations (sorted)."""
        return np.flatnonzero(self.active)

    def any_active(self) -> bool:
        return bool(self.active.any())

    def seed_priors(
        self, hi: np.ndarray, lo: np.ndarray, n_used: np.ndarray
    ) -> None:
        """Seed the DECISION rules with per-cell tallies from a prior run
        of the same cell — the grid's incremental re-analysis warm start
        (ISSUE 17): when a dataset's content changed only incrementally,
        the prior run's exceedance proportions are an informative sample
        of the same-side-of-alpha question, so pooling them into the
        Besag–Clifford ``h`` rule and the Clopper–Pearson decided-at-alpha
        interval lets stable cells retire after ``min_perms`` fresh draws
        (hundreds of permutations) instead of re-earning the full budget.

        Semantics, pinned by tests/test_grid.py:

        - priors enter ``_decided`` ONLY — reported tallies (``hi``/
          ``lo``/``eff``), ``n_used``, and the Phipson–Smyth p-values are
          computed from FRESH draws exclusively, so a warm-started
          result's numbers are exact estimators at its realized stopping
          point;
        - the ``min_perms`` floor applies to fresh draws, so every
          warm-started cell still sees a floor sample of the NEW data
          before any decision can fire;
        - priors ride :meth:`state_arrays`/:meth:`restore_state`
          (``seq_prior_*`` keys), so an interrupted warm-started run
          resumes with identical decisions.

        Must be called before any fold (priors folded mid-run would make
        decisions depend on call order)."""
        if self.folded:
            raise ValueError(
                "seed_priors must be called before any chunk is folded"
            )
        hi = np.asarray(hi, dtype=np.int64)
        lo = np.asarray(lo, dtype=np.int64)
        n_used = np.asarray(n_used, dtype=np.int64).ravel()
        if hi.shape != self.hi.shape or lo.shape != self.lo.shape:
            raise ValueError(
                f"prior tallies have shapes {hi.shape}/{lo.shape}, "
                f"expected {self.hi.shape}"
            )
        if n_used.shape != self.n_used.shape:
            raise ValueError(
                f"prior n_used has shape {n_used.shape}, expected "
                f"{self.n_used.shape}"
            )
        if (hi < 0).any() or (lo < 0).any() or (n_used < 0).any():
            raise ValueError("prior tallies must be non-negative")
        self.prior_hi, self.prior_lo, self.prior_n = hi, lo, n_used

    def counts(self) -> np.ndarray:
        """(n_modules, n_cells) tail-resolved exceedance counts — the same
        convention as :func:`~netrep_tpu.ops.pvalues.exceedance_counts`
        (min tail for two-sided; callers double the p there)."""
        if self.alternative == "greater":
            return self.hi
        if self.alternative == "less":
            return self.lo
        return np.minimum(self.hi, self.lo)

    def state_arrays(self) -> dict[str, np.ndarray]:
        """Checkpointable tallies + retired set (restored by
        :meth:`restore_state`); keys are the checkpoint extras namespace."""
        out = {
            "seq_hi": self.hi,
            "seq_lo": self.lo,
            "seq_n_used": self.n_used,
            "seq_active": self.active,
            "seq_folded": np.int64(self.folded),
        }
        if self.eff is not None:
            out["seq_eff"] = self.eff
        if self.prior_n is not None:
            out["seq_prior_hi"] = self.prior_hi
            out["seq_prior_lo"] = self.prior_lo
            out["seq_prior_n"] = self.prior_n
        return out

    def restore_state(self, extras: dict) -> None:
        """Restore tallies + retired set from checkpoint extras; shape
        mismatches mean the checkpoint belongs to a different problem."""
        try:
            hi, lo = extras["seq_hi"], extras["seq_lo"]
            n_used, active = extras["seq_n_used"], extras["seq_active"]
            folded = extras["seq_folded"]
        except KeyError:
            raise ValueError(
                "checkpoint has no sequential-stopping state (it was "
                "written by a non-adaptive run); resume it with "
                "adaptive=False or delete it"
            ) from None
        if hi.shape != self.hi.shape or active.shape != self.active.shape:
            raise ValueError(
                "checkpoint sequential-stopping state has a different "
                "module/statistic shape; refusing to resume"
            )
        self.hi = np.asarray(hi, dtype=np.int64)
        self.lo = np.asarray(lo, dtype=np.int64)
        self.n_used = np.asarray(n_used, dtype=np.int64)
        self.active = np.asarray(active, dtype=bool)
        self.folded = int(folded)
        self.eff = (
            np.asarray(extras["seq_eff"], dtype=np.int64)
            if "seq_eff" in extras else None
        )
        # warm-start priors ride the checkpoint (additive keys): a resumed
        # warm-started run must decide exactly as the uninterrupted run —
        # restored BEFORE the self-heal below, which consults them
        if "seq_prior_n" in extras:
            self.prior_hi = np.asarray(extras["seq_prior_hi"],
                                       dtype=np.int64)
            self.prior_lo = np.asarray(extras["seq_prior_lo"],
                                       dtype=np.int64)
            self.prior_n = np.asarray(extras["seq_prior_n"],
                                      dtype=np.int64)
        # self-heal: decisions are a pure function of the tallies, so
        # retire anything already decided — covers an interrupt that
        # landed between a fold and its retirement flags
        pos = self.active_positions()
        if pos.size:
            self.active[pos[self._decided(pos)]] = False

    # -- updates ----------------------------------------------------------

    def update(self, vals: np.ndarray, take: int) -> np.ndarray:
        """Fold one chunk's null values for the currently-active modules
        into the tallies and retire freshly-decided modules.

        Parameters
        ----------
        vals : (take, n_active, n_cells) null statistics, module axis in
            :meth:`active_positions` order.
        take : permutations in this chunk.

        Returns
        -------
        Global positions of modules retired by this chunk (possibly empty).
        Decisions depend only on the tallies, so they are identical for an
        interrupted+resumed run evaluating the same chunks.
        """
        pos = self.active_positions()
        vals = np.asarray(vals, dtype=np.float64)
        if vals.shape[:2] != (take, pos.size):
            raise ValueError(
                f"chunk values have shape {vals.shape}, expected "
                f"({take}, {pos.size}, n_cells)"
            )
        obs = self.observed[pos]
        # NaN null entries compare False on both tails — they contribute
        # nothing, matching exceedance_counts' NaN handling. Stage the new
        # tallies and commit them in one statement at the end: a
        # KeyboardInterrupt mid-update must not leave one tail folded and
        # the other not (resume re-folds by `folded`, so a torn commit
        # would double-count; restore_state re-derives the retirement
        # flags, which may lag this commit harmlessly).
        with np.errstate(invalid="ignore"):
            hi, lo = self.hi.copy(), self.lo.copy()
            hi[pos] += (vals >= obs[None]).sum(axis=0)
            lo[pos] += (vals <= obs[None]).sum(axis=0)
        n_used = self.n_used.copy()
        n_used[pos] += int(take)
        self.hi, self.lo, self.n_used, self.folded = (
            hi, lo, n_used, self.folded + int(take)
        )
        newly = pos[self._decided(pos)]
        self.active[newly] = False
        self._emit_retired(newly)
        return newly

    def update_counts(
        self, hi: np.ndarray, lo: np.ndarray, take: int,
        eff: np.ndarray | None = None,
    ) -> np.ndarray:
        """Fold one chunk's *device-computed* per-(module, statistic)
        exceedance tallies for the currently-active modules — the
        streaming-mode (``store_nulls=False``) twin of :meth:`update`:
        the engine already counted ``null >= observed`` / ``null <=
        observed`` inside the chunk dispatch, so no host-side null slice
        exists to re-tally; transfers shrink from O(chunk·modules·cells)
        raw nulls to O(modules·cells) counts per chunk.

        Parameters
        ----------
        hi, lo : (n_active, n_cells) integer exceedance counts for this
            chunk, module axis in :meth:`active_positions` order. Device
            comparisons are f32-vs-f32 on exactly the values the
            materialized path widens to f64, so the folded tallies are
            identical to :meth:`update` on the same chunk — decisions
            cannot diverge between the two modes.
        take : permutations in this chunk.
        eff : optional (n_active, n_cells) valid (non-NaN) draw counts;
            when given they accumulate in :attr:`eff` — the streaming
            path's replacement for reading per-cell validity off the null
            array at p-value time. Folded in the same single-statement
            commit as the tallies, so a Ctrl-C can never tear the two
            apart (the checkpoint stays resume-exact).

        Returns
        -------
        Global positions of modules retired by this chunk, as
        :meth:`update`.
        """
        pos = self.active_positions()
        hi = np.asarray(hi, dtype=np.int64)
        lo = np.asarray(lo, dtype=np.int64)
        want = (pos.size, self.observed.shape[1])
        if hi.shape != want or lo.shape != want:
            raise ValueError(
                f"chunk counts have shapes {hi.shape}/{lo.shape}, expected "
                f"{want}"
            )
        # same torn-commit discipline as update(): stage, then commit in
        # one statement
        new_hi, new_lo = self.hi.copy(), self.lo.copy()
        new_hi[pos] += hi
        new_lo[pos] += lo
        n_used = self.n_used.copy()
        n_used[pos] += int(take)
        new_eff = self.eff
        if eff is not None:
            new_eff = (
                self.eff if self.eff is not None else np.zeros_like(self.hi)
            ).copy()
            new_eff[pos] += np.asarray(eff, dtype=np.int64)
        self.hi, self.lo, self.n_used, self.eff, self.folded = (
            new_hi, new_lo, n_used, new_eff, self.folded + int(take)
        )
        newly = pos[self._decided(pos)]
        self.active[newly] = False
        self._emit_retired(newly)
        return newly

    def force_retire(self, positions=None) -> np.ndarray:
        """Administratively retire modules (LOCAL positions; default: every
        still-active module) regardless of their statistical state — the
        serving layer's per-request retirement view (ISSUE 7): a packed
        request whose permutation budget (or latency SLO) is spent leaves
        the shared dispatch through the same retirement path a
        Besag–Clifford decision takes, so the engine's re-bucketing needs
        no second exit mechanism. Tallies and ``n_used`` are left as
        folded — the sequential Phipson–Smyth p-values at the retirement
        point stay exact. Returns the positions actually retired (already-
        retired ones are skipped)."""
        pos = (
            self.active_positions() if positions is None
            else np.asarray(positions, dtype=np.int64).ravel()
        )
        pos = pos[self.active[pos]]
        self.active[pos] = False
        return pos

    def _emit_retired(self, newly: np.ndarray) -> None:
        """Telemetry for each freshly-retired module: its per-cell
        exceedance tallies and permutation count at the decision point —
        the machine-readable record of WHY the adaptive run stopped
        drawing for it (ISSUE 3). No bus attached = no cost."""
        if self.telemetry is None or not newly.size:
            return
        for p in newly:
            p = int(p)
            self.telemetry.emit(
                "module_retired", module=p,
                n_perm_used=int(self.n_used[p]),
                folded=int(self.folded),
                hi=self.hi[p].tolist(), lo=self.lo[p].tolist(),
                n_active_left=int(self.active.sum()),
            )

    def _decided(self, pos: np.ndarray) -> np.ndarray:
        """Per-module decision mask for the modules at ``pos``: every
        computable cell is settled by the Besag–Clifford ``h`` rule or the
        CP decided-at-alpha rule, and the floor sample is met."""
        rule = self.rule
        out = np.zeros(pos.size, dtype=bool)
        for j, p in enumerate(pos):
            n = int(self.n_used[p])
            # the min_perms floor is on FRESH draws: a warm-started cell
            # still samples the new data before any decision can fire
            if n < rule.min_perms:
                continue
            # warm-start priors (seed_priors) pool into the DECISION
            # counts only — fresh tallies/p-values are reported unchanged
            if self.prior_n is not None:
                hi_c = self.hi[p] + self.prior_hi[p]
                lo_c = self.lo[p] + self.prior_lo[p]
                n = n + int(self.prior_n[p])
            else:
                hi_c, lo_c = self.hi[p], self.lo[p]
            if self.alternative == "greater":
                c, thresh = hi_c, rule.alpha
            elif self.alternative == "less":
                c, thresh = lo_c, rule.alpha
            else:
                # two-sided p is min-tail doubled: the decision boundary on
                # the min-tail proportion is alpha/2
                c, thresh = np.minimum(hi_c, lo_c), rule.alpha / 2
            by_h = c >= rule.h
            cp_lo, cp_hi = _cp_bounds(c, n, 1.0 - rule.confidence)
            by_cp = (cp_lo > thresh) | (cp_hi < thresh)
            out[j] = bool(np.all(by_h | by_cp | self._nan_cells[p]))
        return out

    def total_evaluated(self) -> int:
        """Σ per-module permutations drawn — the adaptive work metric the
        bench row reports against ``n_modules * n_perm``."""
        return int(self.n_used.sum())
