"""Pure-NumPy oracle implementation of the NetRep statistics.

This module is the *reference semantics* for the whole framework: every JAX
kernel in :mod:`netrep_tpu.ops.stats` is tested for parity against these
functions (SURVEY.md §4 "oracle-parity strategy"), and the slow permutation
loop here doubles as the measurable CPU baseline (SURVEY.md §6, BASELINE.md).

Statistic definitions follow the reference's seven module-preservation
statistics (SURVEY.md §2.2 "Statistic kernels", BASELINE.json:5):

- ``avg.weight``  — mean off-diagonal edge weight of the module's test-network
  submatrix.
- ``coherence``   — proportion of the module's (standardized) data variance
  explained by the summary profile; equals the mean squared node contribution.
- ``cor.cor``     — Pearson correlation between the off-diagonal entries of
  the discovery and test correlation submatrices (concordance of correlation
  structure, SURVEY.md §2.2).
- ``cor.degree``  — Pearson correlation between discovery and test
  within-module weighted degree vectors.
- ``cor.contrib`` — Pearson correlation between discovery and test node
  contribution vectors.
- ``avg.cor``     — sign-aware mean correlation density: mean over
  off-diagonal pairs of ``sign(disc_corr) * test_corr`` (discovery signs,
  SURVEY.md §2.2 "sign-aware means using discovery-network signs").
- ``avg.contrib`` — sign-aware mean node contribution: mean over nodes of
  ``sign(disc_contrib) * test_contrib``.

Building blocks (SURVEY.md §2.2):

- summary profile — first left singular vector of the column-standardized
  module data, sign-anchored to correlate positively with the module's mean
  node profile.
- node contribution — Pearson correlation of each node's data with the
  summary profile.
- weighted degree — row sums of the module adjacency submatrix, diagonal
  excluded.

NOTE on provenance: the reference mount ``/root/reference`` is empty
(SURVEY.md §0), so no file:line citations into reference sources are
possible; definitions are built from SURVEY.md §2.2/§3.1 and BASELINE.json:5
and kept self-consistent across oracle, JAX kernels, and the native backend.
"""

from __future__ import annotations

import numpy as np

#: Canonical statistic order used throughout the framework (observed arrays,
#: null arrays, p-value tables). Matches the reference's seven statistics
#: named in BASELINE.json:5.
STAT_NAMES = (
    "avg.weight",
    "coherence",
    "cor.cor",
    "cor.degree",
    "cor.contrib",
    "avg.cor",
    "avg.contrib",
)

#: Statistics computable without a ``data`` matrix (SURVEY.md §2.2
#: "data-less case": avg.weight, cor.cor, cor.degree; data-dependent
#: statistics are NA).
TOPOLOGY_STATS = ("avg.weight", "cor.cor", "cor.degree")

N_STATS = len(STAT_NAMES)


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def standardize(data: np.ndarray) -> np.ndarray:
    """Column-standardize ``data`` (samples x nodes): mean 0, sd 1 (ddof=1).

    Columns with zero variance become all-zero rather than NaN so degenerate
    nodes drop out of downstream statistics.
    """
    data = np.asarray(data, dtype=np.float64)
    mu = data.mean(axis=0, keepdims=True)
    sd = data.std(axis=0, ddof=1, keepdims=True)
    sd = np.where(sd > 0, sd, np.inf)
    return (data - mu) / sd


def summary_profile(data: np.ndarray) -> np.ndarray:
    """Summary profile of a module: first left singular vector of the
    column-standardized data, sign-anchored so it correlates positively with
    the module's mean node profile (SURVEY.md §2.2).

    Parameters
    ----------
    data : (n_samples, n_nodes) module data slice.

    Returns
    -------
    (n_samples,) unit-norm summary profile.
    """
    x = standardize(data)
    u, s, _vt = np.linalg.svd(x, full_matrices=False)
    prof = u[:, 0]
    anchor = x.mean(axis=1)
    if np.dot(prof, anchor) < 0:
        prof = -prof
    return prof


def node_contribution(data: np.ndarray, profile: np.ndarray | None = None) -> np.ndarray:
    """Node contribution: Pearson correlation of each node's data with the
    module summary profile (SURVEY.md §2.2)."""
    x = standardize(data)
    if profile is None:
        profile = summary_profile(data)
    p = profile - profile.mean()
    pn = np.linalg.norm(p)
    xn = np.linalg.norm(x, axis=0)
    denom = pn * xn
    with np.errstate(invalid="ignore", divide="ignore"):
        out = (x.T @ p) / denom
    out[denom == 0] = 0.0
    return out


def module_coherence(data: np.ndarray) -> float:
    """Proportion of the standardized module data's variance explained by the
    summary profile. Equals the mean squared node contribution for
    column-standardized data (SURVEY.md §2.2)."""
    nc = node_contribution(data)
    return float(np.mean(nc**2))


def weighted_degree(net: np.ndarray) -> np.ndarray:
    """Within-module weighted degree: row sums of the module adjacency
    submatrix, diagonal excluded (SURVEY.md §2.2)."""
    net = np.asarray(net, dtype=np.float64)
    return net.sum(axis=1) - np.diag(net)


def avg_edge_weight(net: np.ndarray) -> float:
    """Mean off-diagonal edge weight of the module adjacency submatrix."""
    net = np.asarray(net, dtype=np.float64)
    m = net.shape[0]
    if m < 2:
        return float("nan")
    off = net.sum() - np.trace(net)
    return float(off / (m * (m - 1)))


def pearson(x: np.ndarray, y: np.ndarray) -> float:
    """Plain Pearson correlation with NaN for degenerate inputs."""
    x = np.asarray(x, dtype=np.float64).ravel()
    y = np.asarray(y, dtype=np.float64).ravel()
    xc = x - x.mean()
    yc = y - y.mean()
    denom = np.linalg.norm(xc) * np.linalg.norm(yc)
    if denom == 0:
        return float("nan")
    return float(np.dot(xc, yc) / denom)


def _offdiag(a: np.ndarray) -> np.ndarray:
    m = a.shape[0]
    return a[~np.eye(m, dtype=bool)]


# ---------------------------------------------------------------------------
# Discovery-side fixed properties
# ---------------------------------------------------------------------------

class DiscoveryProps:
    """Per-module discovery-dataset properties that stay fixed across the
    permutation null (SURVEY.md §3.1: the discovery side of every statistic is
    the actual module; only the test-side node set is permuted).

    Attributes
    ----------
    corr : (m, m) discovery correlation submatrix over the module's nodes
        (restricted to nodes present in the test dataset, in discovery order).
    sign_corr : (m, m) elementwise signs of ``corr``.
    degree : (m,) discovery within-module weighted degree.
    contrib : (m,) discovery node contributions (None when data-less).
    sign_contrib : (m,) signs of ``contrib`` (None when data-less).
    """

    def __init__(self, corr: np.ndarray, net: np.ndarray, data: np.ndarray | None):
        self.corr = np.asarray(corr, dtype=np.float64)
        self.sign_corr = np.sign(self.corr)
        self.degree = weighted_degree(net)
        if data is not None:
            self.contrib = node_contribution(data)
            self.sign_contrib = np.sign(self.contrib)
        else:
            self.contrib = None
            self.sign_contrib = None


# ---------------------------------------------------------------------------
# The seven statistics
# ---------------------------------------------------------------------------

def module_stats(
    disc: DiscoveryProps,
    test_corr: np.ndarray,
    test_net: np.ndarray,
    test_data: np.ndarray | None,
) -> np.ndarray:
    """Compute the seven preservation statistics for one candidate test-side
    node set against fixed discovery-side module properties.

    Returns a length-7 vector in :data:`STAT_NAMES` order. Data-dependent
    statistics are NaN when ``test_data``/``disc.contrib`` are absent
    (SURVEY.md §2.2 data-less case).
    """
    out = np.full(N_STATS, np.nan)
    test_corr = np.asarray(test_corr, dtype=np.float64)
    test_net = np.asarray(test_net, dtype=np.float64)

    out[0] = avg_edge_weight(test_net)
    out[2] = pearson(_offdiag(disc.corr), _offdiag(test_corr))
    out[3] = pearson(disc.degree, weighted_degree(test_net))

    if test_data is not None and disc.contrib is not None:
        nc = node_contribution(test_data)
        out[1] = float(np.mean(nc**2))
        out[4] = pearson(disc.contrib, nc)
        out[5] = float(np.mean(_offdiag(disc.sign_corr * test_corr)))
        out[6] = float(np.mean(disc.sign_contrib * nc))
    return out


# ---------------------------------------------------------------------------
# Full permutation procedure (slow loop) — the CPU baseline
# ---------------------------------------------------------------------------

def module_stats_for_indices(
    d_corr: np.ndarray,
    d_net: np.ndarray,
    d_data: np.ndarray | None,
    t_corr: np.ndarray,
    t_net: np.ndarray,
    t_data: np.ndarray | None,
    disc_idx_per_module: list[np.ndarray],
    test_idx_per_module: list[np.ndarray],
) -> np.ndarray:
    """All-module oracle statistics for explicit per-module test-node index
    sets: the shared reconstruction primitive used by the CPU contract test
    (``tests/test_engine.py``) and the on-device deployment check
    (:func:`netrep_tpu.utils.selftest.selftest`), so the two cannot drift
    in how slices map to statistics. Returns ``(n_modules, 7)``."""
    rows = []
    for di, ti in zip(disc_idx_per_module, test_idx_per_module):
        disc = DiscoveryProps(
            d_corr[np.ix_(di, di)],
            d_net[np.ix_(di, di)],
            d_data[:, di] if d_data is not None else None,
        )
        rows.append(module_stats(
            disc,
            t_corr[np.ix_(ti, ti)],
            t_net[np.ix_(ti, ti)],
            t_data[:, ti] if t_data is not None else None,
        ))
    return np.stack(rows)


def permutation_null(
    disc_props: list[DiscoveryProps],
    module_sizes: list[int],
    test_corr: np.ndarray,
    test_net: np.ndarray,
    test_data: np.ndarray | None,
    pool: np.ndarray,
    n_perm: int,
    rng: np.random.Generator,
) -> np.ndarray:
    """Oracle permutation null: the reference's ``PermutationProcedure`` hot
    loop (SURVEY.md §3.1) as a slow NumPy loop.

    For each permutation, one random permutation of the candidate ``pool`` of
    test-node indices is drawn and consecutive chunks of the per-module sizes
    are assigned to modules — so, like the reference's label shuffle, the
    random node sets within one permutation are disjoint across modules.

    Returns ``(n_perm, n_modules, 7)`` null array.
    """
    pool = np.asarray(pool)
    sizes = list(module_sizes)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    assert offsets[-1] <= pool.size, "module sizes exceed candidate pool"
    nulls = np.full((n_perm, len(sizes), N_STATS), np.nan)
    for p in range(n_perm):
        perm = rng.permutation(pool)
        for k, disc in enumerate(disc_props):
            idx = perm[offsets[k]: offsets[k + 1]]
            sub_corr = test_corr[np.ix_(idx, idx)]
            sub_net = test_net[np.ix_(idx, idx)]
            sub_data = test_data[:, idx] if test_data is not None else None
            nulls[p, k] = module_stats(disc, sub_corr, sub_net, sub_data)
    return nulls
