"""Masked pure-JAX kernels for the seven NetRep preservation statistics.

These are the TPU-native equivalents of the reference's C++ statistic kernels
(``netStats.cpp``, SURVEY.md §2.2 / BASELINE.json:5), redesigned for XLA:

- everything is a pure function of arrays → jit/vmap/shard_map compose;
- module-size variability is handled by **pad-to-bucket + mask** (SURVEY.md
  §7 "Hard parts"): every kernel takes a ``(m,)`` validity mask and padded
  entries are provably inert (they contribute zero weight to every mean,
  correlation, Gram matrix, and power-iteration step);
- the summary profile (top left singular vector) is computed by masked power
  iteration on the node-space Gram matrix (fixed iteration count → static
  control flow under jit), or optionally by batched ``eigh`` for exact parity
  (SURVEY.md §7 "Batched SVD on TPU");
- matmuls accumulate in float32 via ``preferred_element_type`` so bfloat16
  inputs stay MXU-friendly without losing the statistics' precision.

Semantics are defined by the NumPy oracle (:mod:`netrep_tpu.ops.oracle`);
oracle-parity is enforced by ``tests/test_stats_oracle.py``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .oracle import N_STATS, STAT_NAMES  # noqa: F401  (canonical order)

_EPS = 1e-30


def _f32(x):
    return jnp.asarray(x, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Masked building blocks
# ---------------------------------------------------------------------------

def masked_mean(x: jnp.ndarray, w: jnp.ndarray, axis=None) -> jnp.ndarray:
    """Mean of ``x`` over entries where ``w`` (0/1 weights) is set."""
    w = _f32(w)
    tot = jnp.sum(w, axis=axis)
    return jnp.sum(_f32(x) * w, axis=axis) / jnp.maximum(tot, _EPS)


def masked_pearson(x: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Pearson correlation of ``x`` and ``y`` over the masked entries of the
    last axis; NaN when either side is degenerate (oracle parity)."""
    w = _f32(w)
    x = _f32(x) * w
    y = _f32(y) * w
    n = jnp.maximum(jnp.sum(w, axis=-1), _EPS)
    mx = jnp.sum(x, axis=-1) / n
    my = jnp.sum(y, axis=-1) / n
    xc = (x - mx[..., None]) * w
    yc = (y - my[..., None]) * w
    cov = jnp.sum(xc * yc, axis=-1)
    vx = jnp.sum(xc * xc, axis=-1)
    vy = jnp.sum(yc * yc, axis=-1)
    denom = jnp.sqrt(vx) * jnp.sqrt(vy)
    return jnp.where(denom > 0, cov / jnp.maximum(denom, _EPS), jnp.nan)


def offdiag_mask(w: jnp.ndarray) -> jnp.ndarray:
    """(m, m) pair mask: both endpoints valid, diagonal excluded."""
    w = _f32(w)
    pair = w[..., :, None] * w[..., None, :]
    m = w.shape[-1]
    return pair * (1.0 - jnp.eye(m, dtype=jnp.float32))


def standardize_masked(data: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Column-standardize ``data`` (..., n_samples, m): mean 0, sd 1 (ddof=1)
    per valid column; invalid or zero-variance columns become all-zero."""
    data = _f32(data) * w[..., None, :]
    ns = data.shape[-2]
    mu = jnp.mean(data, axis=-2, keepdims=True)
    xc = data - mu
    var = jnp.sum(xc * xc, axis=-2, keepdims=True) / jnp.maximum(ns - 1, 1)
    sd = jnp.sqrt(var)
    good = sd > 0
    z = jnp.where(good, xc / jnp.maximum(sd, _EPS), 0.0)
    return z * w[..., None, :]


def weighted_degree_masked(net: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Within-module weighted degree over valid nodes, diagonal excluded."""
    pair = offdiag_mask(w)
    return jnp.sum(_f32(net) * pair, axis=-1)


def summary_profile_masked(
    zdata: jnp.ndarray,
    w: jnp.ndarray,
    n_iter: int = 60,
    method: str = "power",
) -> jnp.ndarray:
    """Summary profile of a (pre-standardized, masked) module data slice:
    top left singular vector, sign-anchored to correlate positively with the
    module's mean node profile (SURVEY.md §2.2).

    ``method='power'`` runs fixed-count masked power iteration on the
    node-space Gram matrix ``G = Z^T Z`` — static shapes and pure matmuls, the
    MXU-friendly replacement for the reference's per-permutation Armadillo SVD
    (SURVEY.md §7 "Batched SVD on TPU"). ``method='eigh'`` uses the exact
    symmetric eigendecomposition (slower under vmap, used for parity tests).

    Parameters
    ----------
    zdata : (..., n_samples, m) standardized masked data (columns of invalid
        nodes all-zero — as produced by :func:`standardize_masked`).
    w : (..., m) validity mask.

    Returns
    -------
    (..., n_samples) unit-norm summary profile.
    """
    w = _f32(w)
    gram = jnp.matmul(
        jnp.swapaxes(zdata, -1, -2), zdata, preferred_element_type=jnp.float32
    )
    if method == "eigh":
        _vals, vecs = jnp.linalg.eigh(gram)
        v = vecs[..., :, -1] * w
    elif method == "power":
        def step(v, _):
            v = jnp.einsum("...ij,...j->...i", gram, v)
            v = v * w
            v = v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True), _EPS)
            return v, None

        # broadcast the start vector to the gram's full batch shape up front —
        # the scan carry must have a fixed type even when the mask carries
        # fewer batch dims than the data (broadcast-batched callers).
        batch = jnp.broadcast_shapes(gram.shape[:-2], w.shape[:-1])
        v0 = jnp.broadcast_to(w, batch + w.shape[-1:])
        v0 = v0 / jnp.maximum(jnp.linalg.norm(v0, axis=-1, keepdims=True), _EPS)
        v, _ = jax.lax.scan(step, v0, None, length=n_iter)
    else:
        raise ValueError(f"unknown summary method: {method!r}")

    prof = jnp.einsum("...si,...i->...s", zdata, v)
    prof = prof / jnp.maximum(jnp.linalg.norm(prof, axis=-1, keepdims=True), _EPS)
    anchor = jnp.sum(zdata, axis=-1)  # ∝ mean node profile over valid nodes
    sign = jnp.sign(jnp.sum(prof * anchor, axis=-1, keepdims=True))
    sign = jnp.where(sign == 0, 1.0, sign)
    return prof * sign


def node_contribution_masked(zdata: jnp.ndarray, prof: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Pearson correlation of each valid node's (standardized) data with the
    summary profile. ``prof`` is mean-zero by construction (columns of
    ``zdata`` are mean-zero), so this reduces to normalized dot products."""
    p = prof - jnp.mean(prof, axis=-1, keepdims=True)
    num = jnp.einsum("...si,...s->...i", zdata, p)
    xn = jnp.linalg.norm(zdata, axis=-2)
    pn = jnp.linalg.norm(p, axis=-1, keepdims=True)
    denom = xn * pn
    nc = jnp.where(denom > 0, num / jnp.maximum(denom, _EPS), 0.0)
    return nc * w


# ---------------------------------------------------------------------------
# Discovery-side fixed properties (device-resident pytree)
# ---------------------------------------------------------------------------

class DiscProps(NamedTuple):
    """Padded per-module discovery-side properties held fixed across the
    permutation null (SURVEY.md §3.1). All arrays are padded to the module's
    bucket capacity ``m`` and masked by ``mask``.

    ``contrib``/``sign_contrib`` are all-zero (and ``has_data`` False) in the
    data-less variant — the kernels then emit NaN for data statistics
    (SURVEY.md §2.2).
    """

    corr: jnp.ndarray          # (..., m, m)
    sign_corr: jnp.ndarray     # (..., m, m)
    degree: jnp.ndarray        # (..., m)
    contrib: jnp.ndarray       # (..., m)
    sign_contrib: jnp.ndarray  # (..., m)
    mask: jnp.ndarray          # (..., m) 0/1


def make_disc_props(corr, net, data, mask, summary_method: str = "eigh") -> DiscProps:
    """Build :class:`DiscProps` from padded discovery submatrices.

    ``data`` may be None (data-less variant). Uses exact ``eigh`` summary by
    default — this runs once per module, not in the hot loop.
    """
    corr = _f32(corr)
    net = _f32(net)
    mask = _f32(mask)
    pair = offdiag_mask(mask)
    corr = corr * pair  # zero padded rows/cols and diagonal influence
    degree = jnp.sum(net * pair, axis=-1)
    if data is not None:
        z = standardize_masked(data, mask)
        prof = summary_profile_masked(z, mask, method=summary_method)
        contrib = node_contribution_masked(z, prof, mask)
    else:
        contrib = jnp.zeros_like(degree)
    return DiscProps(
        corr=corr,
        sign_corr=jnp.sign(corr),
        degree=degree,
        contrib=contrib,
        sign_contrib=jnp.sign(contrib),
        mask=mask,
    )


# ---------------------------------------------------------------------------
# The seven statistics on gathered (padded) test submatrices
# ---------------------------------------------------------------------------

def stats_from_parts(
    disc: DiscProps,
    avg_weight: jnp.ndarray,          # (...,) precomputed mean off-diag weight
    test_degree: jnp.ndarray,         # (..., m) precomputed weighted degree
    test_corr: jnp.ndarray | None,    # (..., m, m) pair-masked, or None
    test_zdata: jnp.ndarray | None,   # (..., n_samples, m) standardized+masked
    n_iter: int = 60,
    summary_method: str = "power",
) -> jnp.ndarray:
    """Assemble the seven statistics from precomputed topology parts — the
    common core of the dense path (parts from the gathered ``test_net``
    submatrix) and the sparse path (parts from padded neighbor lists,
    :mod:`netrep_tpu.ops.sparse`). ``test_corr`` must already be multiplied
    by the off-diagonal pair mask. Statistics whose inputs are absent
    (``test_corr``/``test_zdata`` None) come back NaN (SURVEY.md §2.2)."""
    w = disc.mask
    pair = offdiag_mask(w)
    npair = jnp.maximum(jnp.sum(pair, axis=(-1, -2)), _EPS)
    nanlike = jnp.full_like(_f32(avg_weight), jnp.nan)

    flat = lambda a: a.reshape(*a.shape[:-2], -1)
    if test_corr is not None:
        cor_cor = masked_pearson(flat(disc.corr), flat(test_corr), flat(pair))
    else:
        cor_cor = nanlike

    cor_degree = masked_pearson(disc.degree, test_degree, w)

    if test_zdata is not None:
        prof = summary_profile_masked(test_zdata, w, n_iter=n_iter, method=summary_method)
        nc = node_contribution_masked(test_zdata, prof, w)
        coherence = masked_mean(nc * nc, w, axis=-1)
        cor_contrib = masked_pearson(disc.contrib, nc, w)
        avg_cor = (
            jnp.sum(disc.sign_corr * test_corr, axis=(-1, -2)) / npair
            if test_corr is not None else nanlike
        )
        avg_contrib = masked_mean(disc.sign_contrib * nc, w, axis=-1)
    else:
        coherence = cor_contrib = avg_cor = avg_contrib = nanlike

    return jnp.stack(
        [avg_weight, coherence, cor_cor, cor_degree, cor_contrib, avg_cor, avg_contrib],
        axis=-1,
    )


def module_stats_masked(
    disc: DiscProps,
    test_corr: jnp.ndarray,   # (..., m, m)
    test_net: jnp.ndarray,    # (..., m, m)
    test_zdata: jnp.ndarray | None,  # (..., n_samples, m) standardized+masked
    n_iter: int = 60,
    summary_method: str = "power",
) -> jnp.ndarray:
    """Compute the seven statistics for one (batched) padded test node set.

    Returns ``(..., 7)`` in :data:`~netrep_tpu.ops.oracle.STAT_NAMES` order.
    Data statistics are NaN when ``test_zdata`` is None (SURVEY.md §2.2).
    """
    w = disc.mask
    pair = offdiag_mask(w)
    test_corr = _f32(test_corr) * pair
    test_net = _f32(test_net) * pair
    npair = jnp.maximum(jnp.sum(pair, axis=(-1, -2)), _EPS)

    avg_weight = jnp.sum(test_net, axis=(-1, -2)) / npair
    test_degree = jnp.sum(test_net, axis=-1)

    return stats_from_parts(
        disc, avg_weight, test_degree, test_corr, test_zdata,
        n_iter=n_iter, summary_method=summary_method,
    )


def gather_submatrix_mxu(
    M: jnp.ndarray,        # (n, n) symmetric matrix
    idx_sorted: jnp.ndarray,  # (m,) ASCENDING indices (padded slots = n)
    unsort: jnp.ndarray,   # (m, m) permutation matrix P, P[a, i] = [order[a] == i]
) -> jnp.ndarray:
    """TPU-fast submatrix gather ``M[idx, idx]`` decomposed into ops the
    hardware likes (SURVEY.md §7 "Gather bandwidth" — this is the hot-loop
    access pattern):

    1. **row gather with ascending indices** — whole-row slices are
       DMA-friendly and sorted order restores HBM locality (measured ~50×
       faster than random-order row gathers on the bench chip; the naive
       2D ``M[idx[:,None], idx[None,:]]`` lowers to per-element (1,1)-slice
       gathers at ~15M elements/s);
    2. **column select as a one-hot matmul on the MXU** — selecting m of n
       columns is ``rows @ onehot(idxᵀ)``, exact for 0/1 one-hots;
    3. **unsort via small permutation matmuls** — the statistics pair
       test-side entry i with discovery-side entry i, so the sorted-basis
       submatrix is rotated back with ``Pᵀ S P`` (two (m, m) MXU matmuls)
       instead of on-chip scatter ops.

    Padded slots carry the sentinel ``n``: their row gather clips to row
    n-1 (junk, masked out downstream) and their one-hot column is all-zero.
    """
    n = M.shape[-1]
    m = idx_sorted.shape[-1]
    rows = jnp.take(M, idx_sorted, axis=0, mode="clip")          # (m, n)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (n, m), 0)
    onehot = (col_ids == idx_sorted[None, :]).astype(M.dtype)     # (n, m)
    sub_sorted = jnp.matmul(rows, onehot, preferred_element_type=jnp.float32)
    # rotate back to the original (discovery-paired) order: Pᵀ S P
    out = jnp.matmul(
        jnp.swapaxes(unsort, -1, -2),
        jnp.matmul(sub_sorted, unsort, preferred_element_type=jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out


def gather_and_stats_mxu(
    disc: DiscProps,
    idx: jnp.ndarray,          # (m,) int32 test-node indices (padded)
    test_corr: jnp.ndarray,    # (n, n)
    test_net: jnp.ndarray | None,    # (n, n); None with net_beta set
    test_dataT: jnp.ndarray | None,  # (n, n_samples) TRANSPOSED data
    n_iter: int = 60,
    summary_method: str = "power",
    net_beta: float | None = None,
) -> jnp.ndarray:
    """MXU/DMA-friendly variant of :func:`gather_and_stats` (see
    :func:`gather_submatrix_mxu`), ~10-20x faster on TPU at genome scale,
    where the per-element gather emitter crawls. Value fidelity: the one-hot
    and permutation matmuls are exact selections in exact arithmetic, but
    XLA's default-precision f32 matmul on TPU truncates operands to
    bfloat16, so gathered VALUES carry up to ~4e-3 relative rounding there
    (attenuated ~1/m in the statistics, which average over >= m^2 entries —
    negligible against permutation-null Monte-Carlo noise; see BASELINE.md
    §precision). On backends with true f32 matmuls (CPU) the selection is
    exact. ``test_dataT`` is the data matrix transposed once at engine init
    so the per-instance data slice is a contiguous row gather instead of a
    strided column gather."""
    n = test_corr.shape[-1]
    m = idx.shape[-1]
    w = disc.mask
    # sentinel-pad, sort ascending; padded slots sort to the end
    idx_eff = jnp.where(w > 0, idx, n).astype(jnp.int32)
    order = jnp.argsort(idx_eff)
    idx_sorted = jnp.take(idx_eff, order, axis=0)
    pos = jax.lax.broadcasted_iota(jnp.int32, (m, m), 1)
    unsort = (pos == order[:, None]).astype(test_corr.dtype)      # P (m, m)

    sub_corr = gather_submatrix_mxu(test_corr, idx_sorted, unsort)
    # derived network (net_beta): |corr|**β of the GATHERED submatrix —
    # halves the row traffic of the bandwidth-bound hot loop and avoids the
    # second gather's own bf16 selection rounding (the derived values carry
    # only the corr gather's rounding, amplified ~β× by the power)
    sub_net = (
        derived_net(sub_corr, net_beta) if test_net is None
        else gather_submatrix_mxu(test_net, idx_sorted, unsort)
    )

    if test_dataT is not None:
        rows_d = jnp.take(test_dataT, idx_sorted, axis=0, mode="clip")  # (m, s)
        sub_d = jnp.matmul(
            jnp.swapaxes(unsort, -1, -2), rows_d,
            preferred_element_type=jnp.float32,
        )                                                          # (m, s)
        zdata = standardize_masked(jnp.swapaxes(sub_d, -1, -2), w)
    else:
        zdata = None
    return module_stats_masked(
        disc, sub_corr, sub_net, zdata, n_iter=n_iter, summary_method=summary_method
    )


def gather_zdata(
    test_dataT: jnp.ndarray,   # (n, n_samples) TRANSPOSED data
    idx: jnp.ndarray,          # (..., m) int32 node indices (padded)
    mask: jnp.ndarray,         # (..., m) validity mask
) -> jnp.ndarray:
    """Slice per-module data columns out of the TRANSPOSED data matrix and
    standardize: the single place the (n, n_samples) layout contract lives
    (row gather + swapaxes; see :func:`gather_and_stats` for why the
    transposed layout). Supports leading batch axes on ``idx``."""
    sub_d = jnp.take(test_dataT, idx, axis=0)          # (..., m, n_samples)
    return standardize_masked(jnp.swapaxes(sub_d, -1, -2), mask)


#: soft-threshold constructions `derived_net` can apply (the three WGCNA
#: adjacency types; "unsigned" is the classic |corr|**β). DERIVED_FORMULA
#: holds the human-readable formula per kind for error messages — a new
#: kind is added HERE (both tables) and in derived_net's chain, nowhere
#: else (check_derived_network reuses derived_net itself).
DERIVED_NET_KINDS = ("unsigned", "signed", "signed-hybrid")
DERIVED_FORMULA = {
    "unsigned": "|correlation|**{b}",
    "signed": "((1+correlation)/2)**{b}",
    "signed-hybrid": "max(correlation, 0)**{b}",
}


def normalize_net_beta(net_beta) -> tuple[float, str]:
    """Resolve ``EngineConfig.network_from_correlation``'s two accepted
    spellings — a bare power β (the original knob, meaning unsigned) or a
    ``(β, kind)`` pair — into ``(float, kind)``."""
    if isinstance(net_beta, tuple):
        if len(net_beta) != 2:
            raise ValueError(
                "network_from_correlation must be a power β or a "
                f"(β, kind) pair, got a {len(net_beta)}-tuple: {net_beta!r}"
            )
        beta, kind = net_beta
    else:
        beta, kind = net_beta, "unsigned"
    if kind not in DERIVED_NET_KINDS:
        raise ValueError(
            f"derived-network kind must be one of {DERIVED_NET_KINDS}, "
            f"got {kind!r}"
        )
    try:
        return float(beta), kind
    except (TypeError, ValueError):
        raise ValueError(
            "network_from_correlation power must be numeric, got "
            f"{beta!r}"
        ) from None


def derived_net(sub_corr: jnp.ndarray, net_beta) -> jnp.ndarray:
    """Soft-threshold network submatrix derived on device from the gathered
    correlation (the WGCNA adjacency constructions): ``|corr|**β``
    (unsigned, the default), ``((1+corr)/2)**β`` (signed), or
    ``max(corr, 0)**β`` (signed hybrid). ``net_beta`` is a bare β or a
    ``(β, kind)`` pair. Deriving instead of gathering a stored n×n network
    halves the hot loop's HBM row traffic and the engine's matrix footprint
    (BASELINE.md roofline: the gather is bandwidth-bound) — elementwise
    functions commute with gathers, so the result equals gathering the
    precomputed matrix up to float rounding."""
    beta, kind = normalize_net_beta(net_beta)
    if kind == "signed":
        # clip guards fractional β against NaN when rounding (bf16 mxu
        # selection, or user f32 a ULP below -1) pushes corr under -1
        return jnp.clip((1.0 + sub_corr) * 0.5, 0.0, None) ** beta
    if kind == "signed-hybrid":
        # 0**β = 0 for β > 0, so clipping implements "corr**β where
        # positive, else 0" without a where/NaN hazard at fractional β
        return jnp.clip(sub_corr, 0.0, None) ** beta
    return jnp.abs(sub_corr) ** beta


def gather_and_stats(
    disc: DiscProps,
    idx: jnp.ndarray,          # (..., m) int32 test-node indices (padded)
    test_corr: jnp.ndarray,    # (n, n)
    test_net: jnp.ndarray | None,    # (n, n); None with net_beta set
    test_dataT: jnp.ndarray | None,  # (n, n_samples) TRANSPOSED data
    n_iter: int = 60,
    summary_method: str = "power",
    net_beta: float | None = None,
) -> jnp.ndarray:
    """Gather a module's test submatrices by index and compute the seven
    statistics — the per-permutation unit of work in the reference's hot loop
    (SURVEY.md §3.1: O(m²) gather + kernels), expressed as one fused XLA
    computation. ``idx`` is a single module's ``(m,)`` index vector — batching
    over permutations/modules is done by ``vmap`` of this function. ``idx``
    may carry arbitrary in-range values at padded positions (the mask zeroes
    their influence).

    The 2D advanced-index gather is exact (no matmul in the value path) and,
    measured on TPU v5e in the engine's batched ``(batch, K, m)`` index
    layout, runs at 50-120 Gelem/s — the whole per-permutation submatrix
    extraction (~1M useful elements at north-star shapes) costs ~20 µs.
    ``test_dataT`` is the data matrix transposed once at engine init: the
    per-module data slice is then a row gather; gathering columns of the
    (n_samples, n) layout instead lowers to strided per-element loads on TPU
    (measured ~10x whole-chunk slowdown — the round-1 ``direct`` mode's
    mistake)."""
    sub_corr = test_corr[idx[:, None], idx[None, :]]
    sub_net = (
        derived_net(sub_corr, net_beta) if test_net is None
        else test_net[idx[:, None], idx[None, :]]
    )
    zdata = gather_zdata(test_dataT, idx, disc.mask) if test_dataT is not None else None
    return module_stats_masked(
        disc, sub_corr, sub_net, zdata, n_iter=n_iter, summary_method=summary_method
    )
