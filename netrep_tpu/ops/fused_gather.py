"""Fused submatrix gather: one-HBM-pass Pallas/Mosaic kernel for the hot
loop's access pattern ``M[idx[:, None], idx[None, :]]`` (SURVEY.md §7
"Gather bandwidth"; the reference's per-permutation Armadillo submatrix
slice, SURVEY.md §3.1).

Why a kernel (BASELINE.md roofline, round-2 measurements): the XLA path
(:func:`netrep_tpu.ops.stats.gather_submatrix_mxu`) materializes the
``(cap, n)`` gathered row block in HBM at ~200-300 GB/s, materializes the
``(n, cap)`` one-hot, then re-reads both for the column-select matmul —
several HBM passes over a block that is used exactly once, on a loop that is
bandwidth-bound. This kernel instead:

1. DMAs each needed row of ``M`` directly HBM→VMEM (one contiguous copy per
   row — row order is irrelevant to per-row DMAs, so the argsort /
   unsort-permutation machinery of the mxu path disappears entirely);
2. generates one-hot tiles on the fly in VMEM and accumulates the
   column-select ``rows @ onehot`` on the MXU, tile by tile;
3. writes only the ``(cap, cap)`` selected submatrix back to HBM.

Total HBM traffic: ``cap·n`` read + ``cap²`` written — the algorithm's
ideal for a row-fetch design — versus ~3-5 passes of ``cap·n`` for the XLA
path. Selection values carry the same rounding as the mxu path (the one-hot
matmul runs at the dtype's native MXU precision: exact 0/1 selection in
exact arithmetic; bf16 operand truncation for f32 inputs on TPU — see
BASELINE.md §precision), or ~f32-exact with ``exact=True`` (hi/lo split).

Two entry points share the kernel:

- :func:`gather_submatrix_fused` — replicated (n, n) matrices (the
  single-device / perm-sharded engine path);
- :func:`gather_submatrix_fused_local` — a row-shard's LOCAL block inside
  ``shard_map``: rows owned by other shards are zeroed (ownership mask), so
  a ``psum`` over the row axis assembles the full submatrix
  (:mod:`netrep_tpu.parallel.sharded`, mode='fused').

CPU/testing: ``interpret=True`` runs the kernel in the Pallas interpreter —
used by the parity tests; the engine only selects this path on TPU-like
backends.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Column tile of the in-VMEM one-hot select matmul. 512 lanes keeps the
# (rows, tile) @ (tile, cap) matmuls MXU-shaped while bounding the one-hot
# value to tile·cap·4 B.
_COL_TILE = 512
# Max rows DMA'd/resident per grid step: bounds the VMEM rows buffer to
# 128·(n rounded to tile)·itemsize (10.5 MB at n=20k f32).
_ROW_BLOCK = 128
# Outstanding row DMAs per grid step (semaphore-array size): a rolling
# window — copy a reuses sem[a % _DMA_WINDOW] after waiting out its
# previous user. 16 × 80 KB rows ≈ 1.3 MB in flight, ample to hide issue
# latency, while keeping the semaphore footprint small (a per-row array of
# up to 128 risks Mosaic resource limits).
_DMA_WINDOW = 16
# Budget for the VMEM rows scratch (ADVICE r3): rb·(n_tiles·_COL_TILE)·
# itemsize is 10.5 MB at n=20k f32 with rb=128 — larger gene counts would
# exceed TPU VMEM (~16 MiB/core, shared with the out block and one-hot
# tiles) and fail Mosaic compilation. _row_block picks the minimal-padding
# sublane-aligned block whose scratch fits this budget, or raises advising
# gather_mode='mxu'.
_VMEM_BUDGET = 8 * 1024 * 1024


def run_dma_window(copy, count: int, owned=None) -> None:
    """Issue ``count`` row DMAs through the rolling ``_DMA_WINDOW``-slot
    semaphore window — the shared row-DMA machinery of this module's gather
    kernel and the fused-statistics kernel (:mod:`netrep_tpu.ops.fused_stats`).
    ``copy(a)`` builds the async copy for slot ``a``; ``owned(a)`` (optional)
    predicates slots whose DMA is skipped entirely (negative row ids in the
    row-sharded gather). Copy ``a`` rides semaphore ``a % _DMA_WINDOW`` after
    waiting out that slot's previous user; the tail drain waits only
    ``[count - _DMA_WINDOW, count)`` (earlier copies were waited during the
    start loop — widening it would double-wait)."""
    if owned is None:
        def owned(a):  # noqa: E306 — every slot owned (replicated kernels)
            return jnp.bool_(True)

    def start(a, _):
        # index clamp: the guard predicate is ANDed with a >= window, but
        # the operand itself must never read SMEM out of bounds
        prev = jnp.maximum(a - _DMA_WINDOW, 0)

        @pl.when((a >= _DMA_WINDOW) & owned(prev))
        def _wait_prev():
            copy(prev).wait()

        @pl.when(owned(a))
        def _go():
            copy(a).start()
        return _

    def drain(a, _):
        @pl.when(owned(a))
        def _go():
            copy(a).wait()
        return _

    jax.lax.fori_loop(0, count, start, None, unroll=8)
    jax.lax.fori_loop(max(0, count - _DMA_WINDOW), count, drain, None,
                      unroll=8)


def select_columns(rows_buf, cols, n_cols: int, n_tiles: int, *,
                   exact: bool, own=None) -> jnp.ndarray:
    """In-VMEM one-hot column select of ``cols`` from a DMA'd row buffer —
    the shared select stage of the gather and fused-statistics kernels.
    ``rows_buf`` is an (rb, n_tiles·_COL_TILE) VMEM block; returns the
    (rb, len(cols)) f32 selection, accumulated tile by tile on the MXU.
    ``own`` (optional, (rb,)) zeroes un-owned rows with a SELECT before the
    dot (never a multiply: un-owned slots skipped their DMA, so the buffer
    holds uninitialized VMEM — 0·NaN would poison the dot and, sharded,
    the psum). ``exact`` applies the hi/lo bf16 split restoring ~f32-exact
    selection on TPU MXUs (see :func:`gather_submatrix_fused`)."""
    rb = rows_buf.shape[0]
    acc = jnp.zeros((rb, cols.shape[0]), jnp.float32)
    for t in range(n_tiles):
        c0 = t * _COL_TILE
        tile = rows_buf[:, c0: c0 + _COL_TILE]
        if (t + 1) * _COL_TILE > n_cols:
            # final tile spills past n_cols: the buffer tail is
            # uninitialized VMEM — zero it so 0·garbage (potential NaN)
            # cannot reach the accumulator through the dot
            in_range = (
                c0 + jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1)
                < n_cols
            )
            tile = jnp.where(in_range, tile, 0)
        if own is not None:
            tile = jnp.where(own[:, None] != 0, tile, jnp.zeros_like(tile))
        col_ids = c0 + jax.lax.broadcasted_iota(
            jnp.int32, (_COL_TILE, cols.shape[0]), 0
        )
        onehot = (col_ids == cols[None, :]).astype(tile.dtype)
        if exact and tile.dtype == jnp.float32:
            # hi/lo split: TPU MXU truncates f32 dot operands to bf16, so a
            # single dot rounds the selected VALUES (~4e-3 rel). Splitting
            # x = bf16(x) + bf16(x - bf16(x)) and summing two dots restores
            # ~f32-exact selection for 2x the (non-dominant) FLOPs at the
            # same one-pass HBM traffic — vs ~10x cost for gather_mode=
            # 'direct', the only previous exact-on-TPU option.
            hi = tile.astype(jnp.bfloat16)
            lo = (tile - hi.astype(jnp.float32)).astype(jnp.bfloat16)
            oh16 = onehot.astype(jnp.bfloat16)
            acc += jax.lax.dot(hi, oh16, preferred_element_type=jnp.float32)
            acc += jax.lax.dot(lo, oh16, preferred_element_type=jnp.float32)
        else:
            acc += jax.lax.dot(
                tile, onehot, preferred_element_type=jnp.float32
            )
    return acc


def _row_block(cap: int, n_cols: int, itemsize: int) -> int:
    """Row-block size for a fused-gather launch after the VMEM guard.
    Two-step choice: (1) the largest sublane-aligned block that fits the
    ``rb x (col-tile-padded n_cols)`` scratch budget fixes the grid-step
    count ``k = ceil(cap / limit)``; (2) within that step count, the
    SMALLEST aligned block — padded rows skip their DMA but still pay the
    select matmul and out-block writes, so minimizing ``k·rb - cap``
    matters more than maximizing rb (e.g. cap=128 at n=20k f32 → rb=64,
    two zero-pad steps, not rb=96 → 64 padded rows). Raises when even the
    smallest block busts the budget. Module-level (not inlined in ``_run``)
    so ``benchmarks/traffic_model.py`` can reproduce the kernel's REAL
    padding in its CostEstimate cross-check."""
    n_col_tiles = -(-n_cols // _COL_TILE)
    row_bytes = n_col_tiles * _COL_TILE * itemsize
    fit = max(8, _VMEM_BUDGET // row_bytes // 8 * 8)
    limit = min(cap, _ROW_BLOCK, fit)
    if limit * row_bytes > _VMEM_BUDGET:
        raise ValueError(
            f"fused gather scratch needs {limit * row_bytes / 2**20:.1f} MiB "
            f"of VMEM at the smallest row block ({limit} rows x {n_cols} "
            f"cols, itemsize {itemsize}); over the "
            f"{_VMEM_BUDGET / 2**20:.0f} MiB budget — use gather_mode='mxu' "
            "(or bfloat16 storage) at this scale"
        )
    k = -(-cap // limit)            # grid steps at the largest fitting block
    rows_per_step = -(-cap // k)    # smallest block covering cap in k steps
    return min(limit, (rows_per_step + 7) // 8 * 8)


def _kernel(rowidx_smem, M_ref, colidx_ref, own_ref, out_ref, rows_buf, sems,
            *, n_rows: int, n_cols: int, rb: int, n_tiles: int, exact: bool):
    """One grid step: DMA ``rb`` rows of ``M`` (row indices from the
    scalar-prefetched ``rowidx_smem`` — pre-clamped into ``[0, n_rows)`` by
    the caller), zero the rows this instance does not own (``own_ref`` —
    sentinel/padded slots in the replicated case, other shards' rows in the
    row-sharded case), and column-select against the instance's ``cap``
    column indices.

    Refs: rowidx_smem (G, R) SMEM int32 (R = rb-padded row count); M_ref
    (n_rows, n_cols) HBM; colidx_ref (1, cap) VMEM int32; own_ref (1, rb)
    VMEM 0/1 row-ownership for THIS row block; out_ref (1, rb, cap) VMEM;
    rows_buf (rb, n_tiles·tile) VMEM scratch; sems (min(rb, _DMA_WINDOW),)
    DMA semaphores reused modularly — copy ``a`` rides slot
    ``a % _DMA_WINDOW`` after waiting out that slot's previous copy, and
    the tail drain waits only ``[rb - _DMA_WINDOW, rb)`` (earlier copies
    were waited during the start loop; widening it would double-wait).
    """
    g = pl.program_id(0)
    r = pl.program_id(1)

    def row_copy(a):
        src = jnp.clip(rowidx_smem[g, r * rb + a], 0, n_rows - 1)
        return pltpu.make_async_copy(
            M_ref.at[pl.ds(src, 1), :],
            rows_buf.at[pl.ds(a, 1), pl.ds(0, n_cols)],
            sems.at[a % _DMA_WINDOW],
        )

    def owned(a):
        # un-owned slots carry a NEGATIVE row index: their DMA is skipped
        # entirely (a row-sharded shard fetches ONLY its own rows —
        # aggregate row traffic stays cap·n, not D·cap·n) and their buffer
        # content is ignored via the where-mask below
        return rowidx_smem[g, r * rb + a] >= 0

    # rolling window: start copy a after waiting out the previous user of
    # its semaphore slot (copy a - _DMA_WINDOW), then drain the tail
    run_dma_window(row_copy, rb, owned=owned)

    cols = colidx_ref[0, :]                    # (cap,) int32
    own = own_ref[0, :]                        # (rb,) 0/1 for THIS block
    acc = select_columns(rows_buf, cols, n_cols, n_tiles, exact=exact,
                         own=own)
    out_ref[0] = acc.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "exact"))
def _run(M, row_idx, col_idx, own, *, interpret: bool, exact: bool):
    """Flat-batched kernel launch: ``M`` (n_rows, n_cols); ``row_idx``
    (G, cap) local row indices; ``col_idx`` (G, cap) column indices;
    ``own`` (G, cap) 0/1 row-ownership. Returns (G, cap, cap) f32."""
    n_rows, n_cols = M.shape
    G, cap = row_idx.shape
    rb = _row_block(cap, n_cols, M.dtype.itemsize)
    n_row_blocks = -(-cap // rb)
    rpad = n_row_blocks * rb
    if rpad != cap:
        # pad the ROW axis so every grid step owns exactly rb rows; padded
        # slots are un-owned (negative row index: DMA skipped, contribution
        # zeroed)
        pad = ((0, 0), (0, rpad - cap))
        row_idx = jnp.pad(row_idx, pad, constant_values=-1)
        own = jnp.pad(own, pad)
    n_tiles = -(-n_cols // _COL_TILE)

    kernel = functools.partial(
        _kernel, n_rows=n_rows, n_cols=n_cols, rb=rb, n_tiles=n_tiles,
        exact=exact,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(G, n_row_blocks),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # M stays in HBM
            pl.BlockSpec((1, cap), lambda g, r, *_: (g, 0)),   # column idx
            pl.BlockSpec((1, rb), lambda g, r, *_: (g, r)),    # ownership
        ],
        out_specs=pl.BlockSpec((1, rb, cap), lambda g, r, *_: (g, r, 0)),
        scratch_shapes=[
            pltpu.VMEM((rb, n_tiles * _COL_TILE), M.dtype),
            pltpu.SemaphoreType.DMA((min(rb, _DMA_WINDOW),)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((G, rpad, cap), jnp.float32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * G * rpad * n_tiles * _COL_TILE * cap,
            bytes_accessed=(
                G * cap * n_cols * M.dtype.itemsize + G * rpad * cap * 4
            ),
            transcendentals=0,
        ),
    )(row_idx, M, col_idx, own.astype(jnp.float32))
    return out[:, :cap, :] if rpad != cap else out


def gather_submatrix_fused(
    M: jnp.ndarray,     # (n, n)
    idx: jnp.ndarray,   # (..., cap) int32; sentinel >= n at padded slots
    *,
    interpret: bool = False,
    exact: bool = False,
) -> jnp.ndarray:
    """Batched fused submatrix gather over a replicated matrix:
    ``out[..., a, b] = M[idx[..., a], idx[..., b]]`` with sentinel
    (out-of-range) slots yielding zero rows AND zero columns. Returns f32
    ``(..., cap, cap)``.

    ``idx`` needs NO sort: per-row DMA cost is order-independent, unlike the
    mxu path's XLA gather (which needs ascending rows for DMA locality).

    ``exact=True`` (f32 inputs only) selects values hi/lo-split over two
    bf16 dots, restoring ~f32-exact selection on TPU where the single-dot
    path carries bf16 operand truncation. bf16 inputs are always exact (the
    stored values are selected bit-true).
    """
    batch = idx.shape[:-1]
    cap = idx.shape[-1]
    flat = idx.reshape(-1, cap).astype(jnp.int32)
    own = (flat >= 0) & (flat < M.shape[0])
    rows = jnp.where(own, flat, -1)  # negative => DMA skipped in-kernel
    out = _run(M, rows, flat, own, interpret=interpret, exact=exact)
    return out.reshape(*batch, cap, cap)


def gather_submatrix_fused_local(
    block: jnp.ndarray,   # (rows_per, n) — THIS shard's row block
    idx: jnp.ndarray,     # (..., cap) int32 GLOBAL indices
    row_start,            # scalar: first global row this shard owns
    *,
    interpret: bool = False,
    exact: bool = False,
) -> jnp.ndarray:
    """Row-sharded variant for use inside ``shard_map``: DMA only the rows
    of ``idx`` that fall inside this shard's block, zero the rest, and
    column-select against the full (global) index set. The return value is
    this shard's ADDITIVE contribution — ``psum`` over the row axis
    assembles the full submatrix (the caller does the psum;
    :mod:`netrep_tpu.parallel.sharded` mode='fused')."""
    rows_per = block.shape[0]
    batch = idx.shape[:-1]
    cap = idx.shape[-1]
    flat = idx.reshape(-1, cap).astype(jnp.int32)
    rel = flat - row_start
    own = (rel >= 0) & (rel < rows_per) & (flat < block.shape[1])
    rows = jnp.where(own, rel, -1)  # un-owned rows: DMA skipped in-kernel
    out = _run(block, rows, flat, own, interpret=interpret, exact=exact)
    return out.reshape(*batch, cap, cap)
