"""Compute kernels: NumPy oracle semantics (`oracle`), JAX masked statistic
kernels (`stats`), exact permutation p-values (`pvalues`), and the
sequential early-stopping monitor for adaptive nulls (`sequential`)."""
