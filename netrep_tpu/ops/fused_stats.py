"""Pallas mega-kernel: gather + the seven preservation statistics + tally
accumulation fused in VMEM (ISSUE 8; ROADMAP item 1).

Why a mega-kernel (BENCH_r01–r05 roofline trajectory): with
``gather_mode='fused'`` the submatrix *extraction* already runs as one HBM
pass (:mod:`netrep_tpu.ops.fused_gather`), but the seven statistics and the
streaming tally fold stay XLA-composed — the gathered ``(cap, cap)`` blocks
round-trip HBM between the gather, each statistic pass (XLA re-reads the
block ~3–5× across the Gram/Pearson/degree kernels), and the exceedance
comparison. On a bandwidth-bound loop those passes are the remaining
distance to the <60 s north-star. This kernel instead, per permutation and
module:

1. DMAs the module's ``cap`` rows HBM→VMEM in ``rb``-row blocks (the
   row-DMA machinery of :func:`netrep_tpu.ops.fused_gather.run_dma_window`,
   shared — not copied);
2. column-selects each block against the module's index set on the MXU
   (:func:`netrep_tpu.ops.fused_gather.select_columns`, shared) into a
   VMEM-resident ``(cap, cap)`` submatrix — plus the stored network's rows
   when the engine is not in derived-network mode, and the module's data
   rows from the transposed data matrix;
3. computes all seven preservation statistics (avg.weight, coherence via
   the fixed-count power iteration, cor.cor, cor.degree, cor.contrib,
   avg.cor, avg.contrib) entirely in VMEM by calling the SAME
   :func:`netrep_tpu.ops.stats.module_stats_masked` the XLA paths run —
   one formula site, so the kernel can never compute different statistics
   than the engine;
4. writes the ``(7,)`` statistics row back (the materialized-null
   contract) and — in counts mode — compares against the observed
   statistics and accumulates ``(hi, lo, eff)`` int32 tallies in a VMEM
   accumulator that is written to HBM once per grid sweep: O(modules·7)
   counts leave the chip per kernel call, the PR-2 streaming-tally carry
   contract.

Total HBM traffic per permutation: ``Σ cap·n`` read once (+ ``cap·s`` data
rows) and O(K·7) written — versus the XLA composition's several passes
over the gathered blocks plus the full ``(C, K, 7)`` statistics transfer.

Parity contract (pinned in tests/test_fused_stats.py, interpret mode on
CPU tier-1):

- **within stat_mode='fused'**: the counts-mode tallies equal
  ``tail_counts`` of the values-mode null bit-for-bit — both outputs come
  from the same in-kernel statistics registers, the exact analogue of the
  PR-2 streaming↔materialized contract;
- **against the XLA path**: statistics agree at float-rounding level
  (~1e-7 — the same drift class as re-partitioning ``lax.map``, which the
  autotune cache has always documented), and the resulting counts,
  p-values, and adaptive retirement decisions are pinned EQUAL on the CI
  fixtures. On TPU the one-hot selection carries MXU bf16 rounding like
  every fused/mxu gather (``fused_exact`` restores ~f32-exact selection);
  device agreement is held to ``selftest`` tolerance, not bit equality.

CPU/testing: ``interpret=True`` runs the kernel in the Pallas interpreter
(the tier-1 parity surface); the engine selects the compiled path only on
TPU-like backends (``EngineConfig.stat_mode``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import stats as jstats
from .fused_gather import (
    _COL_TILE, _DMA_WINDOW, _ROW_BLOCK, _VMEM_BUDGET, run_dma_window,
    select_columns,
)
from .oracle import N_STATS

#: floor for the rows-buffer budget after the stats kernel's extra VMEM
#: residents (submatrices, data rows, discovery blocks) are subtracted
#: from the shared gather budget — below this even an 8-row block cannot
#: stream usefully and the caller should use stat_mode='xla'.
_MIN_ROWS_BUDGET = 1 << 20


def _stats_scratch_bytes(cap: int, capp: int, s_pad: int, itemsize: int,
                         has_net: bool, has_data: bool) -> int:
    """Non-rows-buffer VMEM the kernel holds resident per grid step: the
    selected submatrices, the data-row block, and the per-module discovery
    blocks (corr + sign_corr dominate)."""
    subs = capp * cap * 4 * (2 if has_net else 1)
    # derived-net mode still materializes sub_net from sub_corr in registers
    subs = max(subs, capp * cap * 4 + cap * cap * 4)
    data = capp * s_pad * itemsize if has_data else 0
    disc = 2 * cap * cap * 4 + 4 * cap * 4
    return subs + data + disc


def resolve_row_block(cap: int, n_cols: int, itemsize: int,
                      s_pad: int = 0, has_net: bool = False,
                      has_data: bool = False,
                      override: int | None = None) -> int:
    """Row-block size for one fused-stats launch: the gather kernel's
    :func:`~netrep_tpu.ops.fused_gather._row_block` policy applied to the
    budget REMAINING after this kernel's extra VMEM residents. ``override``
    (the autotune cache's best-measured block,
    :func:`netrep_tpu.utils.autotune.resolve_fused_rowblock`) is honored
    after sublane alignment and the same budget guard."""
    extra = _stats_scratch_bytes(cap, -(-cap // 8) * 8, s_pad, itemsize,
                                 has_net, has_data)
    budget = _VMEM_BUDGET - extra
    if budget < _MIN_ROWS_BUDGET:
        raise ValueError(
            f"fused-stats scratch needs {extra / 2**20:.1f} MiB of VMEM "
            f"before any row buffer (cap {cap}, {n_cols} cols); use "
            "stat_mode='xla' at this scale"
        )
    n_col_tiles = -(-n_cols // _COL_TILE)
    row_bytes = n_col_tiles * _COL_TILE * itemsize
    fit = max(8, budget // row_bytes // 8 * 8)
    cap8 = -(-cap // 8) * 8
    limit = min(cap8, _ROW_BLOCK, fit)
    if limit * row_bytes > budget:
        raise ValueError(
            f"fused-stats row buffer needs {limit * row_bytes / 2**20:.1f} "
            f"MiB at the smallest block ({limit} rows x {n_cols} cols); "
            "use stat_mode='xla' (or bfloat16 storage) at this scale"
        )
    if override is not None and override >= 8:
        return min(max(8, override // 8 * 8), limit)
    # same minimal-padding policy as the gather kernel's _row_block: fix the
    # step count at the largest fitting block, then take the smallest
    # aligned block covering cap in that many steps
    k = -(-cap // limit)
    rows_per_step = -(-cap // k)
    return min(limit, (rows_per_step + 7) // 8 * 8)


def _kernel(idx_s, pvalid_s, refs, *, n: int, s: int, cap: int, capp: int,
            rb: int, n_tiles: int, n_iter: int, summary_method: str,
            net_beta, has_net: bool, has_data: bool, counts: bool,
            exact: bool):
    """One grid step = one (permutation, module) cell; see module docstring.

    Refs (order fixed by :func:`_call`): ``M_ref`` (n, n) HBM correlation;
    ``N_ref`` (n, n) HBM network (stored-net mode only); ``D_ref`` (n, s)
    HBM transposed data (data mode only); the six DiscProps fields as
    per-module VMEM blocks; ``obs_ref`` (1, 7) (counts mode only);
    ``vals_ref`` (1, 1, 7) out; ``hi/lo/eff`` (K, 7) int32 VMEM
    accumulators (counts mode only — constant index map keeps them
    resident across the whole grid sweep, written back once);
    ``subc_buf``/``subn_buf`` (capp, cap) selected submatrices;
    ``rows_buf`` (rb, tiles·_COL_TILE) DMA target; ``dbuf`` (capp, s_pad)
    data rows; ``sems`` DMA semaphores.
    """
    it = iter(refs)
    M_ref = next(it)
    N_ref = next(it) if has_net else None
    D_ref = next(it) if has_data else None
    dcorr, dsign, ddeg, dcon, dsgn, dmask = (next(it) for _ in range(6))
    obs_ref = next(it) if counts else None
    vals_ref = next(it)
    if counts:
        hi_ref, lo_ref, eff_ref = next(it), next(it), next(it)
    subc_buf = next(it)
    subn_buf = next(it) if has_net else None
    rows_buf = next(it)
    dbuf = next(it) if has_data else None
    sems = next(it)

    b = pl.program_id(0)
    k = pl.program_id(1)
    n_rblocks = capp // rb
    cols = idx_s[b, pl.ds(k * cap, cap)]       # (cap,) int32 module indices

    def dma_rows(src_ref, dst_buf, row_of, count, width):
        def copy(a):
            return pltpu.make_async_copy(
                src_ref.at[pl.ds(row_of(a), 1), :],
                dst_buf.at[pl.ds(a, 1), pl.ds(0, width)],
                sems.at[a % _DMA_WINDOW],
            )
        run_dma_window(copy, count)

    def src_row(a):
        # overflow slots of the final row block re-fetch the last real row
        # (their select output lands in submatrix rows >= cap, never read);
        # sentinel/padded module slots carry index 0 like the XLA paths'
        # _idx_blocks padding — junk either way, masked out by the stats
        return jnp.clip(idx_s[b, k * cap + jnp.minimum(a, cap - 1)],
                        0, n - 1)

    # correlation rows: DMA rb at a time, select into the resident submatrix
    for r in range(n_rblocks):
        dma_rows(M_ref, rows_buf,
                 lambda a, r=r: src_row(r * rb + a), rb, n)
        subc_buf[pl.ds(r * rb, rb), :] = select_columns(
            rows_buf, cols, n, n_tiles, exact=exact
        )
    if has_net:
        for r in range(n_rblocks):
            dma_rows(N_ref, rows_buf,
                     lambda a, r=r: src_row(r * rb + a), rb, n)
            subn_buf[pl.ds(r * rb, rb), :] = select_columns(
                rows_buf, cols, n, n_tiles, exact=exact
            )
    if has_data:
        # data rows are a straight copy (no select): the per-module slice of
        # the TRANSPOSED data matrix is exactly take(tdT, idx) — bit-exact
        # on every backend, unlike the matmul-selected matrices
        dma_rows(D_ref, dbuf, src_row, cap, s)

    sub_c = subc_buf[0:cap, :][None]                       # (1, cap, cap)
    sub_n = (
        subn_buf[0:cap, :][None] if has_net
        else jstats.derived_net(sub_c, net_beta)
    )
    mask1 = dmask[...]                                     # (1, cap)
    disc1 = jstats.DiscProps(
        corr=dcorr[...], sign_corr=dsign[...], degree=ddeg[...],
        contrib=dcon[...], sign_contrib=dsgn[...], mask=mask1,
    )
    if has_data:
        zdata = jnp.swapaxes(dbuf[0:cap, 0:s], 0, 1)[None]  # (1, s, cap)
        zdata = jstats.standardize_masked(zdata, mask1)
    else:
        zdata = None
    stats = jstats.module_stats_masked(
        disc1, sub_c, sub_n, zdata, n_iter=n_iter,
        summary_method=summary_method,
    )                                                      # (1, 7)
    vals_ref[0, 0, :] = stats[0]

    if counts:
        @pl.when((b == 0) & (k == 0))
        def _init():
            hi_ref[...] = jnp.zeros_like(hi_ref)
            lo_ref[...] = jnp.zeros_like(lo_ref)
            eff_ref[...] = jnp.zeros_like(eff_ref)

        # identical comparison semantics to the XLA fold
        # (engine.make_count_buckets): f32 >= / <= on the very registers the
        # values output writes, NaN comparing False on both tails, the
        # perm-validity flag excluding padded tail draws
        ob = obs_ref[0]                                    # (7,)
        v = pvalid_s[b, 0] > 0
        hi_ref[pl.ds(k, 1), :] += ((stats >= ob[None]) & v).astype(jnp.int32)
        lo_ref[pl.ds(k, 1), :] += ((stats <= ob[None]) & v).astype(jnp.int32)
        eff_ref[pl.ds(k, 1), :] += (
            (~jnp.isnan(stats)) & v
        ).astype(jnp.int32)


def _call(tc, tn, tdT, disc, idx, pvalid, obs, *, net_beta, n_iter,
          summary_method, interpret, exact, counts, row_block=None):
    """Build and invoke the pallas_call for one (B, K, cap) batch."""
    B, K, cap = idx.shape
    n = tc.shape[-1]
    has_net = tn is not None
    has_data = tdT is not None
    s = int(tdT.shape[-1]) if has_data else 0
    s_pad = -(-max(s, 1) // 128) * 128
    rb = resolve_row_block(
        cap, n, tc.dtype.itemsize, s_pad=s_pad, has_net=has_net,
        has_data=has_data, override=row_block,
    )
    capp = -(-cap // rb) * rb
    n_tiles = -(-n // _COL_TILE)
    kern = functools.partial(
        lambda idx_s, pvalid_s, *refs, **kw: _kernel(
            idx_s, pvalid_s, refs, **kw
        ),
        n=n, s=s, cap=cap, capp=capp, rb=rb, n_tiles=n_tiles,
        n_iter=n_iter, summary_method=summary_method, net_beta=net_beta,
        has_net=has_net, has_data=has_data, counts=counts, exact=exact,
    )
    blk_mm = pl.BlockSpec((1, cap, cap), lambda b, k, *_: (k, 0, 0))
    blk_m = pl.BlockSpec((1, cap), lambda b, k, *_: (k, 0))
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]        # corr in HBM
    operands = [tc]
    if has_net:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        operands.append(tn)
    if has_data:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        operands.append(tdT)
    in_specs += [blk_mm, blk_mm, blk_m, blk_m, blk_m, blk_m]
    operands += [disc.corr, disc.sign_corr, disc.degree, disc.contrib,
                 disc.sign_contrib, disc.mask]
    if counts:
        in_specs.append(
            pl.BlockSpec((1, N_STATS), lambda b, k, *_: (k, 0))
        )
        operands.append(obs)
    out_specs = [pl.BlockSpec((1, 1, N_STATS), lambda b, k, *_: (b, k, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, K, N_STATS), jnp.float32)]
    if counts:
        # tallies as full blocks with a CONSTANT index map: the accumulator
        # stays VMEM-resident across the whole (B, K) sweep and is flushed
        # to HBM once — the O(modules·7) output contract
        out_specs += [
            pl.BlockSpec((K, N_STATS), lambda b, k, *_: (0, 0))
            for _ in range(3)
        ]
        out_shape += [
            jax.ShapeDtypeStruct((K, N_STATS), jnp.int32) for _ in range(3)
        ]
    scratch = [pltpu.VMEM((capp, cap), jnp.float32)]
    if has_net:
        scratch.append(pltpu.VMEM((capp, cap), jnp.float32))
    scratch.append(pltpu.VMEM((rb, n_tiles * _COL_TILE), tc.dtype))
    if has_data:
        scratch.append(pltpu.VMEM((capp, s_pad), tdT.dtype))
    scratch.append(
        pltpu.SemaphoreType.DMA((min(max(rb, cap), _DMA_WINDOW),))
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, K),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    row_bytes = cap * n * tc.dtype.itemsize * (2 if has_net else 1)
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            # select matmuls + the seven statistics' Gram/power-iteration
            # flops (Gram s·cap² + n_iter·cap² matvecs, per module)
            flops=2 * B * K * (
                capp * n_tiles * _COL_TILE * cap * (2 if has_net else 1)
                + s * cap * cap + n_iter * cap * cap
            ),
            bytes_accessed=B * K * (row_bytes + cap * max(s, 0) * 4)
            + B * K * N_STATS * 4,
            transcendentals=B * K * cap * 2,
        ),
    )(
        idx.reshape(B, K * cap).astype(jnp.int32),
        pvalid.astype(jnp.int32).reshape(B, 1),
        *operands,
    )
    return outs


def fused_stats_values(tc, tn, tdT, disc, idx, *, net_beta=None,
                       n_iter=60, summary_method="power",
                       interpret=False, exact=False, row_block=None):
    """Materialized-mode entry point: the seven statistics for one
    ``(B, K, cap)`` index batch, gathered and computed in VMEM. Returns
    ``(B, K, 7)`` f32 — the same per-chunk contract as the XLA chunk body,
    so the materialized null loops consume it unchanged. ``tn`` None means
    derived-network mode (``net_beta``); ``tdT`` None the data-less
    variant (data statistics NaN)."""
    (vals,) = _call(
        tc, tn, tdT, disc, idx,
        jnp.ones((idx.shape[0],), jnp.int32), None,
        net_beta=net_beta, n_iter=n_iter, summary_method=summary_method,
        interpret=interpret, exact=exact, counts=False, row_block=row_block,
    )
    return vals


def fused_stats_counts(tc, tn, tdT, disc, idx, pvalid, obs, *,
                       net_beta=None, n_iter=60, summary_method="power",
                       interpret=False, exact=False, row_block=None):
    """Streaming-mode entry point: gather + statistics + tally fold in one
    kernel sweep. ``pvalid`` (B,) gates each permutation's contribution
    (the tail-chunk validity mask); ``obs`` (K, 7) f32 are the observed
    statistics the in-VMEM comparison runs against. Returns
    ``(values, hi, lo, eff)`` — values ``(B, K, 7)`` f32 (the registers the
    counts were compared from; callers may discard them, they cost only
    O(B·K·7) HBM) and int32 ``(K, 7)`` tally deltas satisfying
    ``hi == sum((values >= obs) & pvalid)`` etc. bit-for-bit."""
    return _call(
        tc, tn, tdT, disc, idx, pvalid, obs,
        net_beta=net_beta, n_iter=n_iter, summary_method=summary_method,
        interpret=interpret, exact=exact, counts=True, row_block=row_block,
    )


# ---------------------------------------------------------------------------
# Ring exchange (row-sharded path)
# ---------------------------------------------------------------------------

def ring_shift_collective(block, axis_name: str, n_shards: int):
    """Rotate each shard's row block to its right neighbor — the default
    ring-exchange step of the row-sharded fused-stats path. Implemented as
    ``jax.lax.ppermute``, which XLA lowers to a collective-permute: on TPU
    ICI that IS a neighbor DMA (each chip talks only to its ring
    neighbor), and on the CPU test mesh it is an exact, interpretable
    stand-in — one algorithm, testable in tier-1."""
    return jax.lax.ppermute(
        block, axis_name,
        perm=[(j, (j + 1) % n_shards) for j in range(n_shards)],
    )


def _ring_dma_kernel(x_ref, out_ref, send_sem, recv_sem, *, neighbor_of):
    """In-kernel neighbor DMA (SNIPPETS [1]–[3] right-permute pattern):
    push this shard's whole block to the right neighbor's output buffer
    with one ``pltpu.make_async_remote_copy``."""
    copy = pltpu.make_async_remote_copy(
        src_ref=x_ref,
        dst_ref=out_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=neighbor_of(),
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    copy.start()
    copy.wait()


def ring_shift_dma(block, axis_name: str, n_shards: int,
                   mesh_axis_names: tuple):
    """Experimental in-kernel ring step: the SNIPPETS [1]–[3]
    ``make_async_remote_copy`` right-permute, for real-TPU runs where the
    exchange should ride explicit per-neighbor DMA instead of the XLA
    collective (enable with ``NETREP_RING_DMA=1``; the collective path is
    the default and the only one CI can execute). ``mesh_axis_names`` is
    the full mesh axis order — the remote device id names coordinates on
    every mesh axis, keeping the copy inside the ring's row column."""
    def neighbor_of():
        right = jax.lax.rem(
            jax.lax.axis_index(axis_name) + 1, jnp.int32(n_shards)
        )
        return tuple(
            right if name == axis_name else jax.lax.axis_index(name)
            for name in mesh_axis_names
        )

    return pl.pallas_call(
        functools.partial(_ring_dma_kernel, neighbor_of=neighbor_of),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
    )(block)


def ring_gather_all(mats, idx_list, axis_name: str, n_shards: int,
                    rows_per: int, *, interpret=False, exact=False,
                    use_dma=False, mesh_axis_names=()):
    """Assemble full ``(…, cap, cap)`` submatrices from row-sharded
    matrices by streaming row blocks around the ring: at step t this shard
    holds the block originally owned by shard ``(me − t) mod R``, adds its
    additive contribution for EVERY bucket's index set (the per-shard
    Pallas gather kernel,
    :func:`netrep_tpu.ops.fused_gather.gather_submatrix_fused_local` — DMA
    only the rows the resident block owns), and passes the block to the
    right neighbor. After R steps every submatrix entry received exactly
    one nonzero contribution — bit-exact assembly, like the psum it
    replaces, but via R−1 neighbor exchanges instead of an all-reduce, and
    with the row axis now carrying its own permutation shard (the caller
    splits the chunk over BOTH mesh axes, so the row axis multiplies
    permutation parallelism instead of duplicating it). One ring sweep
    serves ALL buckets and ALL matrices (corr [+ stored net]) — each block
    is exchanged R−1 times per chunk total, not per gather.

    ``mats``: list of ``(rows_per, n)`` local blocks (one ring per
    matrix, rotated in lockstep); ``idx_list``: one ``(…, cap)`` GLOBAL
    index batch per bucket. Returns ``subs[mat][bucket]``."""
    from .fused_gather import gather_submatrix_fused_local

    me = jax.lax.axis_index(axis_name)
    subs = [
        [jnp.zeros(idx.shape + (idx.shape[-1],), jnp.float32)
         for idx in idx_list]
        for _ in mats
    ]
    blocks = list(mats)
    for t in range(n_shards):
        row_start = (
            jax.lax.rem(me - t + n_shards, jnp.int32(n_shards)) * rows_per
        )
        for mi, blk in enumerate(blocks):
            for bi, idx in enumerate(idx_list):
                subs[mi][bi] = subs[mi][bi] + gather_submatrix_fused_local(
                    blk, idx, row_start, interpret=interpret, exact=exact,
                )
        if t < n_shards - 1:
            blocks = [
                ring_shift_dma(b, axis_name, n_shards, mesh_axis_names)
                if use_dma
                else ring_shift_collective(b, axis_name, n_shards)
                for b in blocks
            ]
    return subs
