"""Device-mesh helpers — the framework's distributed communication backend
(SURVEY.md §2.3 last row, §5): the reference is single-process OpenMP with no
network backend; the TPU-native equivalent is a `jax.sharding.Mesh` whose
axes carry XLA collectives over ICI (within a slice) and DCN (across hosts).

Axes used by this framework:

- ``perm`` — data parallelism over permutations (the reference's OpenMP axis).
- ``row``  — tensor-style sharding of the n×n correlation/network matrices
  across devices (the large-``n`` scale axis, SURVEY.md §5 "long-context");
  module gathers then assemble submatrices with ``psum`` collectives
  (:mod:`netrep_tpu.parallel.sharded`).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

PERM_AXIS = "perm"
ROW_AXIS = "row"


def make_mesh(
    n_perm_shards: int | None = None,
    n_row_shards: int = 1,
    devices=None,
) -> Mesh:
    """Build a ``(perm, row)`` mesh over the available devices.

    Defaults to all devices on the permutation axis (the embarrassingly
    parallel axis — the right default for networks that fit in one HBM).
    ``n_row_shards > 1`` trades permutation parallelism for matrix sharding
    when the three n×n matrices exceed a single device's HBM
    (SURVEY.md §2.3 "tensor/model parallelism" row: 20k×20k f32 ≈ 1.6 GB
    each; 50k² ≈ 10 GB each).

    On multi-host deployments ``jax.devices()`` spans all hosts and the
    ``perm`` axis rides DCN between hosts while ``row`` should stay within a
    host's ICI domain (devices are laid out perm-major to that end).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if n_perm_shards is None:
        if n % n_row_shards:
            raise ValueError(
                f"{n} devices not divisible by n_row_shards={n_row_shards}"
            )
        n_perm_shards = n // n_row_shards
    need = n_perm_shards * n_row_shards
    if need > n:
        raise ValueError(
            f"mesh {n_perm_shards}×{n_row_shards} needs {need} devices, "
            f"have {n}"
        )
    grid = np.array(devices[:need]).reshape(n_perm_shards, n_row_shards)
    return Mesh(grid, (PERM_AXIS, ROW_AXIS))


def mesh_spec(mesh: Mesh | None):
    """``(devices, n_perm_shards, n_row_shards)`` of a mesh, or None —
    the lightweight record the elastic ladder keeps of the ORIGINAL
    capacity so the grow-back rung can rebuild it after the superseded
    :class:`Mesh` object (and the engine arrays sharded over it) have
    been dropped. Device handles are cheap; the arrays are not."""
    if mesh is None:
        return None
    return (
        tuple(mesh.devices.flat),
        int(mesh.shape.get(PERM_AXIS, mesh.devices.size)),
        int(mesh.shape.get(ROW_AXIS, 1)),
    )


def mesh_from_spec(spec) -> Mesh | None:
    """Rebuild a mesh from :func:`mesh_spec` — the grow-back rung."""
    if spec is None:
        return None
    devices, n_perm, n_row = spec
    return make_mesh(
        n_perm_shards=n_perm, n_row_shards=n_row, devices=list(devices)
    )


def shrink_mesh(devices, like: Mesh) -> Mesh:
    """Rebuild a ``(perm, row)`` mesh over the surviving device subset
    (elastic shrink rung, ISSUE 6), preserving as much of the old mesh's
    row-sharding as still divides the survivor count: the row axis gets
    the largest common divisor of (survivors, old row size) — so a
    row-sharded engine keeps row sharding whenever it can, and collapses
    to ``row=1`` (replicated matrices) only when it must. Everything
    else rides the permutation axis, the embarrassingly parallel one."""
    n = len(devices)
    if n < 1:
        raise ValueError("shrink_mesh needs at least one surviving device")
    old_row = int(like.shape.get(ROW_AXIS, 1))
    row = max(
        f for f in range(1, old_row + 1) if n % f == 0 and old_row % f == 0
    )
    return make_mesh(
        n_perm_shards=n // row, n_row_shards=row, devices=list(devices)
    )
