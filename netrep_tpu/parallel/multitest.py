"""Multi-test-dataset vmap path — Config C (BASELINE.json:9; SURVEY.md §2.3
"multi-dataset parallelism"): the reference loops (discovery, test) pairs
sequentially in R; on TPU, when several test cohorts share one node universe
(the common consortium design: same genes measured in every cohort), the
engine vmaps the whole permutation kernel over a stacked (T, n, n) test-matrix
axis — one compiled program sharing one permutation index batch across all T
cohorts.

What that buys, measured (BASELINE.md Config C row): code-path parity with
multi-device meshes and one compile instead of T, NOT single-chip speedup at
genome scale — at 5k genes one cohort already saturates the chip (vmapped
1.03× vs sequential on TPU v5e). The vmap stacking wins where each cohort
under-fills the device (small n: 1.27× at toy scale on CPU) or where the T
axis maps onto a mesh axis.

Statistical note: the same permutation node-sets are reused across the T test
datasets within one run. Nulls remain valid per pair (each dataset's matrices
are independent of the shared index draw); only the *joint* distribution
across datasets is coupled, which the reference's sequential independent runs
don't expose either way because p-values are computed per pair.

Config C composes with Config D (``matrix_sharding='row'``): each cohort's
n×n matrices are row-sharded individually across the mesh's row axis and the
chunk program loops the small T axis over the shared permutation index batch
— a multi-cohort genome-scale consortium run holds T×n²/D_row per device
instead of T×n² (VERDICT r1 item 7).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import stats as jstats
from ..ops.oracle import N_STATS
from ..utils.checkpoint import content_digest as ckpt_digest
from ..utils.config import EngineConfig
from .engine import ModuleSpec, PermutationEngine


class MultiTestEngine:
    """Permutation engine for one discovery dataset against T stacked test
    datasets with identical node universes.

    Wraps :class:`PermutationEngine` for bucket construction (discovery-side
    properties, sizes, pool validation) and adds a dataset axis to the test
    side of every kernel via vmap.
    """

    def __init__(
        self,
        disc_corr, disc_net, disc_data,
        test_corrs,   # (T, n, n)
        test_nets,    # (T, n, n)
        test_datas,   # list of (samples_t, n) per dataset (ragged ok) or None
        modules: Sequence[ModuleSpec],
        pool: np.ndarray,
        config: EngineConfig = EngineConfig(),
        mesh=None,
    ):
        test_corrs = np.asarray(test_corrs)
        self.T = test_corrs.shape[0]
        # Mesh-shape-independent test-side checkpoint identity (ISSUE 6):
        # digest the host inputs before padding/sharding/transpose — see
        # PermutationEngine.fingerprint_digest for the contract
        self._host_test_digest = ckpt_digest(
            [np.asarray(test_corrs), np.asarray(test_nets)]
            + ([] if test_datas is None
               else [np.asarray(d) for d in test_datas])
        )
        # Base engine: discovery-side buckets + pool validation only — no
        # throwaway test-side device transfer (the test side lives here).
        # With matrix_sharding='row' it also builds the sharded gatherers
        # (discovery_only + row path in PermutationEngine.__init__).
        self._base = PermutationEngine(
            disc_corr, disc_net,
            disc_data if test_datas is not None else None,
            None, None, None,
            modules, pool, config=config, mesh=mesh, discovery_only=True,
        )
        self.row_sharded = self._base.row_sharded
        self.net_beta = self._base.net_beta  # sample-checked per dataset below
        dtype = jnp.dtype(config.dtype)
        if self.net_beta is not None:
            from .engine import check_derived_network

            for t in range(self.T):
                check_derived_network(
                    test_corrs[t], test_nets[t], self.net_beta, f"test[{t}]",
                )
        if self.row_sharded:
            # Config C × Config D composition (VERDICT r1 item 7): each test
            # dataset's n×n matrices are row-sharded individually and the
            # chunk program loops the (small) T axis over the shared
            # permutation index batch — the stacked (T, n, n) tensor never
            # materializes on one device, and permutation draws stay shared
            # across cohorts exactly as on the replicated vmap path.
            from .mesh import ROW_AXIS
            from .sharded import pad_square_to_multiple, shard_rows

            d_row = mesh.shape[ROW_AXIS]
            self._tc = [
                shard_rows(
                    jnp.asarray(pad_square_to_multiple(c, d_row), dtype), mesh
                )
                for c in test_corrs
            ]
            self._tn = (
                None if self.net_beta is not None
                else [
                    shard_rows(
                        jnp.asarray(pad_square_to_multiple(m, d_row), dtype),
                        mesh,
                    )
                    for m in np.asarray(test_nets)
                ]
            )
        else:
            self._tc = jnp.asarray(test_corrs, dtype)
            self._tn = (
                None if self.net_beta is not None
                else jnp.asarray(test_nets, dtype)
            )
        # ragged sample counts across datasets are allowed → keep a list and
        # vmap only when uniform, else python-loop the T axis for data.
        # Data is stored TRANSPOSED — (T, n, samples) — so per-module slices
        # are row gathers (see ops.stats.gather_and_stats).
        if test_datas is None:
            self._td = None
            self._uniform_samples = True
        else:
            shapes = {np.asarray(d).shape for d in test_datas}
            self._uniform_samples = len(shapes) == 1
            if self._uniform_samples and not self.row_sharded:
                self._td = jnp.asarray(
                    np.stack([np.asarray(d).T for d in test_datas]), dtype
                )
            else:
                # per-dataset list (ragged samples, or row-sharded — where
                # the T axis is a host-side loop and `td[t]` must be free
                # Python list indexing, not an eager device slice)
                self._td = [jnp.asarray(np.asarray(d).T, dtype) for d in test_datas]
        self.config = config
        self.mesh = mesh
        # The bf16 screened fast-pass (ISSUE 16) exists only on the single-
        # test engine's chunk programs; the T-axis programs here always run
        # f32. 'auto' resolves to f32 silently, an explicit ask refuses.
        if getattr(config, "null_precision", "auto") == "bf16_rescue":
            raise ValueError(
                "null_precision='bf16_rescue' is not supported on the "
                "multi-test engine (vmap_tests=True); use 'auto' or 'f32', "
                "or run tests sequentially"
            )
        # Statistics execution mode (ISSUE 8): the T-axis fused path loops
        # the cohorts over the shared index blocks, each cohort's rows
        # gathered+reduced by the mega-kernel. The ring-exchange row-sharded
        # composition is single-test only — 'auto' falls back to the XLA
        # composition there, an explicit 'fused' refuses loudly.
        self.stat_mode = self._base.stat_mode
        if self.stat_mode == "fused" and self.row_sharded:
            if config.stat_mode == "fused":
                raise ValueError(
                    "stat_mode='fused' with matrix_sharding='row' is not "
                    "supported on the multi-test engine; use the single-"
                    "test engine's ring path or stat_mode='xla'"
                )
            self.stat_mode = "xla"
            # keep the base engine's chunk rounding consistent (its ring
            # predicate would otherwise round the chunk over both axes)
            self._base.stat_mode = "xla"
        self.modules = self._base.modules
        self.n_modules = self._base.n_modules
        self._chunk_cached: Callable | None = None
        self._obs_fn_cached: Callable | None = None
        #: jitted streaming programs keyed by (adaptive, observed bytes) —
        #: see PermutationEngine._stream_super_fn; cleared by rebucket
        self._stream_cached: dict = {}

    def release(self) -> None:
        """Drop device arrays and cached programs (see
        :meth:`PermutationEngine.release`) — base engine included."""
        self._base.release()
        self._tc = self._tn = self._td = None
        self._chunk_cached = None
        self._obs_fn_cached = None
        self._stream_cached = {}
        self.mesh = None

    # -- kernel composition ------------------------------------------------

    def _stats_stack(self, summary_method: str):
        """vmap composition: modules → (optionally) permutations → datasets."""
        one = partial(
            jstats.gather_and_stats,
            n_iter=self.config.power_iters,
            summary_method=summary_method,
            net_beta=self.net_beta,
        )
        over_mod = jax.vmap(one, in_axes=(0, 0, None, None, None))
        return over_mod

    def _tn_at(self, t):
        """Per-dataset network operand: None in derived-network mode."""
        return None if self._tn is None else self._tn[t]

    def observed(self) -> np.ndarray:
        """(T, n_modules, 7) observed statistics."""
        out = np.full((self.T, self.n_modules, N_STATS), np.nan)
        if self.row_sharded:
            if self._obs_fn_cached is None:
                from .engine import make_row_sharded_observed

                self._obs_fn_cached = make_row_sharded_observed(
                    self._base._gather_rep, self.net_beta
                )
            _obs = self._obs_fn_cached
            for t in range(self.T):
                td_t = None if self._td is None else self._td[t]
                for b in self._base.buckets:
                    res = _obs(
                        b.disc, b.obs_idx, self._tc[t], self._tn_at(t), td_t
                    )
                    out[t, b.module_pos] = np.asarray(res, dtype=np.float64)
            return out
        over_mod = self._stats_stack("eigh")
        if self._td is None or self._uniform_samples:
            over_test = jax.jit(jax.vmap(
                over_mod,
                in_axes=(None, None, 0, None if self._tn is None else 0,
                         None if self._td is None else 0),
            ))
            for b in self._base.buckets:
                res = over_test(b.disc, b.obs_idx, self._tc, self._tn, self._td)
                out[:, b.module_pos] = np.asarray(res, dtype=np.float64)
        else:
            fn = jax.jit(over_mod)
            for t in range(self.T):
                for b in self._base.buckets:
                    res = fn(b.disc, b.obs_idx, self._tc[t], self._tn_at(t),
                             self._td[t])
                    out[t, b.module_pos] = np.asarray(res, dtype=np.float64)
        return out

    def _fused_chunk_body(self) -> Callable:
        """Unjitted fused-kernel chunk for the multi-test path: scan over
        perm sub-batches; per batch the T cohorts loop over the SHARED
        index blocks, each cohort's submatrices extracted by the one-pass
        Pallas kernel (:mod:`netrep_tpu.ops.fused_gather`). Mirrors
        ``PermutationEngine``'s fused branch; T divides the batch so the
        per-dispatch submatrix working set stays bounded. Jitting /
        mesh-wrapping happens in :meth:`_finish_chunk`."""
        import jax

        from .engine import _idx_blocks, fused_scan, make_fused_gather

        cfg = self.config
        base = self._base
        T = self.T
        td_absent = self._td is None
        tn_absent = self._tn is None
        net_beta = self.net_beta
        caps_slices = [(b.cap, tuple(b.slices)) for b in base.buckets]
        gsf = make_fused_gather(cfg)
        # real effective chunk (not a sentinel) so an explicit cfg.perm_batch
        # clamps exactly like the single-test engine's (ADVICE r3)
        pb = cfg.resolved_perm_batch(
            "fused", jax.default_backend(), base.effective_chunk()
        )
        # measured-throughput override of the byte-budget heuristic, same
        # mechanism as the single-test chunk (utils/autotune.py); the key
        # carries T so multi-cohort measurements never cross-pollinate
        from ..utils.autotune import resolve_perm_batch

        at_key = base.autotune_key(extra=f"T{T}")
        perm_batch, at_cache = resolve_perm_batch(
            cfg, at_key, max(1, pb // T)
        )
        base._autotune_record = (
            (at_cache, at_key, perm_batch) if at_cache is not None else None
        )

        def chunk(keys, pool, tc, tn, td, discs):
            C = keys.shape[0]

            def batch_body(_, keys_b):
                perm = jax.vmap(
                    lambda k: jax.random.permutation(k, pool)
                )(keys_b)
                outs_b = []
                for (cap, slices), disc in zip(caps_slices, discs):
                    idx_b = _idx_blocks(perm, cap, slices)  # (B, K, cap)
                    per_t = []
                    for t in range(T):
                        sub_c = gsf(tc[t], idx_b)
                        sub_n = (
                            jstats.derived_net(sub_c, net_beta)
                            if tn_absent else gsf(tn[t], idx_b)
                        )
                        zd = (
                            jstats.gather_zdata(td[t], idx_b, disc.mask)
                            if not td_absent else None
                        )
                        per_t.append(jstats.module_stats_masked(
                            disc, sub_c, sub_n, zd,
                            n_iter=cfg.power_iters,
                            summary_method=cfg.summary_method,
                        ))
                    outs_b.append(jnp.stack(per_t))  # (T, B, K, 7)
                return None, outs_b

            outs, Cp = fused_scan(keys, perm_batch, batch_body)
            # per bucket: (Cp//B, T, B, K, 7) -> (T, C, K, 7), pad dropped
            return [
                o.swapaxes(0, 1).reshape(T, Cp, *o.shape[3:])[:, :C]
                for o in outs
            ]

        return chunk

    def _fused_perm_batch(self) -> tuple:
        """Resolved perm batch + autotune record for the fused-STATS
        T-axis chunk (mirrors :meth:`_fused_chunk_body`'s resolution; the
        key carries T and the fused-stats mode suffix via the base
        engine's :meth:`~netrep_tpu.parallel.engine.PermutationEngine.
        autotune_key`)."""
        from ..utils.autotune import resolve_perm_batch

        base = self._base
        cfg = self.config
        pb = cfg.resolved_perm_batch(
            "fused", jax.default_backend(), base.effective_chunk()
        )
        at_key = base.autotune_key(extra=f"T{self.T}")
        perm_batch, at_cache = resolve_perm_batch(
            cfg, at_key, max(1, pb // self.T)
        )
        base._autotune_record = (
            (at_cache, at_key, perm_batch) if at_cache is not None else None
        )
        return perm_batch

    def _fused_stats_chunk_body(self) -> Callable:
        """Unjitted fused-STATS chunk for the multi-test path (ISSUE 8):
        per perm sub-batch the T cohorts loop over the SHARED index
        blocks, each cohort's module rows gathered, reduced to the seven
        statistics, and written back by ONE mega-kernel sweep per
        (cohort, bucket) (:func:`netrep_tpu.ops.fused_stats.
        fused_stats_values`). Output layout matches every other multi-test
        chunk: per-bucket ``(T, C, K, 7)``."""
        from .engine import _idx_blocks, fused_scan, make_fused_stats

        cfg = self.config
        base = self._base
        T = self.T
        td_absent = self._td is None
        tn_absent = self._tn is None
        net_beta = self.net_beta
        caps_slices = [(b.cap, tuple(b.slices)) for b in base.buckets]
        vals_fn, _ = make_fused_stats(cfg)
        rb = base._fused_rowblock
        perm_batch = self._fused_perm_batch()

        def chunk(keys, pool, tc, tn, td, discs):
            C = keys.shape[0]

            def batch_body(_, keys_b):
                perm = jax.vmap(
                    lambda k: jax.random.permutation(k, pool)
                )(keys_b)
                outs_b = []
                for (cap, slices), disc in zip(caps_slices, discs):
                    idx_b = _idx_blocks(perm, cap, slices)  # (B, K, cap)
                    per_t = [
                        vals_fn(
                            tc[t], None if tn_absent else tn[t],
                            None if td_absent else td[t], disc, idx_b,
                            net_beta=net_beta, row_block=rb,
                        )
                        for t in range(T)
                    ]
                    outs_b.append(jnp.stack(per_t))  # (T, B, K, 7)
                return None, outs_b

            outs, Cp = fused_scan(keys, perm_batch, batch_body)
            return [
                o.swapaxes(0, 1).reshape(T, Cp, *o.shape[3:])[:, :C]
                for o in outs
            ]

        return chunk

    def _fused_count_chunk(self, axis_name) -> Callable:
        """Fused-STATS counter for the multi-test streaming paths: the
        T-axis twin of :meth:`~netrep_tpu.parallel.engine.
        PermutationEngine._fused_count_chunk` — per (cohort, bucket) the
        mega-kernel folds ``(hi, lo, eff)`` in VMEM and the per-batch
        ``(K, 7)`` deltas stack into the ``(T, K, 7)`` tally layout the
        multi-test carry holds."""
        from .engine import (
            _idx_blocks, make_fused_stats, shard_chunk_offset,
        )
        from ..ops.oracle import N_STATS

        cfg = self.config
        base = self._base
        T = self.T
        td_absent = self._td is None
        tn_absent = self._tn is None
        net_beta = self.net_beta
        caps_slices = [(b.cap, tuple(b.slices)) for b in base.buckets]
        sizes_k = [len(b.module_pos) for b in base.buckets]
        _, counts_fn = make_fused_stats(cfg)
        rb = base._fused_rowblock
        perm_batch = self._fused_perm_batch()

        def count_chunk(keys_c, valid_c, chunk_ops, obs_b):
            pool, tc, tn, td, discs = chunk_ops
            C = keys_c.shape[0]
            B = min(perm_batch, C)
            nb = -(-C // B)
            Cp = nb * B
            keys_p = (
                jnp.concatenate(
                    [keys_c, keys_c[-1:].repeat(Cp - C, axis=0)]
                ) if Cp != C else keys_c
            )
            pos = jnp.arange(Cp, dtype=jnp.int32)
            col0 = (
                shard_chunk_offset(axis_name, C)
                if axis_name is not None else 0
            )
            pvalid = (
                (pos < C) & ((pos + col0) < valid_c)
            ).astype(jnp.int32)
            init = [
                tuple(
                    jnp.zeros((T, k, N_STATS), jnp.int32) for _ in range(3)
                )
                for k in sizes_k
            ]

            def body(carry, xs):
                keys_b, pv_b = xs
                perm = jax.vmap(
                    lambda kk: jax.random.permutation(kk, pool)
                )(keys_b)
                new = []
                for (cap, slices), disc, ob, ts in zip(
                        caps_slices, discs, obs_b, carry):
                    idx_b = _idx_blocks(perm, cap, slices)
                    per_t = [
                        counts_fn(
                            tc[t], None if tn_absent else tn[t],
                            None if td_absent else td[t], disc, idx_b,
                            pv_b, ob[t], net_beta=net_beta, row_block=rb,
                        )[1:]
                        for t in range(T)
                    ]
                    hi_t = jnp.stack([p[0] for p in per_t])
                    lo_t = jnp.stack([p[1] for p in per_t])
                    eff_t = jnp.stack([p[2] for p in per_t])
                    new.append(
                        (ts[0] + hi_t, ts[1] + lo_t, ts[2] + eff_t)
                    )
                return new, None

            deltas, _ = jax.lax.scan(
                body, init,
                (keys_p.reshape(nb, B, *keys_p.shape[1:]),
                 pvalid.reshape(nb, B)),
            )
            if axis_name is not None:
                deltas = jax.lax.psum(deltas, axis_name)
            return deltas

        return count_chunk

    def _finish_chunk(self, chunk, chunk_args, fused_rep: bool) -> Callable:
        """Jit (and, with a mesh, shard) a chunk body. ``fused_rep`` marks
        the fused replicated-matrices path, whose pallas_call XLA cannot
        auto-partition: the whole chunk then runs under shard_map (keys
        split on the perm axis, all other operands replicated — same
        treatment as ``PermutationEngine._build_chunk_fn``)."""
        cfg = self.config
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .distributed import to_global

            ksh = NamedSharding(self.mesh, P(cfg.mesh_axis))
            osh = [
                NamedSharding(self.mesh, P(None, cfg.mesh_axis))
                for _ in self._base.buckets
            ]
            if fused_rep:
                from .sharded import _NO_CHECK_KW, _shard_map

                chunk = _shard_map(
                    chunk,
                    mesh=self.mesh,
                    in_specs=(
                        (P(cfg.mesh_axis),) + (P(),) * len(chunk_args)
                    ),
                    # outputs are (T, C, K, 7): perm axis is dim 1
                    out_specs=P(None, cfg.mesh_axis),
                    **_NO_CHECK_KW,
                )
            jitted = jax.jit(chunk, out_shardings=osh)
            self._chunk_cached = lambda keys: jitted(
                to_global(keys, ksh), *chunk_args
            )
        else:
            jitted = jax.jit(chunk)
            self._chunk_cached = lambda keys: jitted(keys, *chunk_args)
        return self._chunk_cached

    def _chunk_fn(self) -> Callable:
        if self._chunk_cached is not None:
            return self._chunk_cached
        chunk, chunk_args, fused_rep = self._chunk_parts()
        return self._finish_chunk(chunk, chunk_args, fused_rep=fused_rep)

    def _chunk_parts(self) -> tuple:
        """(unjitted chunk, chunk operands, fused_rep flag) — the chunk
        program before jit/mesh wrapping, shared by :meth:`_chunk_fn` and
        the streaming (``store_nulls=False``) builders so the two dispatch
        modes evaluate the identical per-chunk computation."""
        cfg = self.config
        base = self._base
        uniform = self._td is None or self._uniform_samples
        td_absent = self._td is None
        T = self.T
        caps_slices = [(b.cap, tuple(b.slices)) for b in base.buckets]
        over_mod = self._stats_stack(cfg.summary_method)
        over_perm = jax.vmap(over_mod, in_axes=(None, 0, None, None, None))

        # device operands are jit ARGUMENTS, not closure captures — captured
        # device arrays become compile-time constants (T·n² baked into the
        # executable at multi-cohort scale)
        chunk_args = (
            base._pool_dev, self._tc, self._tn, self._td,
            [b.disc for b in base.buckets],
        )

        row_sharded = self.row_sharded
        gather_perm = base._gather_perm if row_sharded else None
        net_beta = self.net_beta
        tn_absent = self._tn is None
        if row_sharded:
            from .sharded import gather_corr_net

        if self.stat_mode == "fused":
            # fused-stats chunk (ISSUE 8): replicated path only (the
            # row-sharded composition downgraded in __init__); needs the
            # whole-chunk shard_map treatment on a mesh, like the fused
            # gather (pallas_call cannot be auto-partitioned)
            return self._fused_stats_chunk_body(), chunk_args, True

        fused_rep = base.gather_mode == "fused" and not row_sharded
        if fused_rep:
            return self._fused_chunk_body(), chunk_args, True

        def chunk(keys, pool, tc, tn, td, discs):
            perm = jax.vmap(lambda k: jax.random.permutation(k, pool))(keys)
            outs = []
            for (cap, slices), disc in zip(caps_slices, discs):
                cols = []
                for off, size in slices:
                    idx = perm[:, off: off + size]
                    cols.append(jnp.pad(idx, ((0, 0), (0, cap - size))))
                idx_b = jnp.stack(cols, axis=1)  # (C, K, cap)
                if row_sharded:
                    # Config C × row sharding: T is small — loop datasets
                    # over the SHARED index batch; each cohort's submatrices
                    # assemble from its own row-sharded matrices (psum over
                    # the row axis), never materializing (T, n, n) anywhere.
                    per_t = []
                    for t in range(T):
                        sub_c, sub_n = gather_corr_net(
                            gather_perm, tc[t],
                            None if tn_absent else tn[t], idx_b, net_beta,
                        )
                        zd = (
                            jstats.gather_zdata(td[t], idx_b, disc.mask)
                            if not td_absent else None
                        )
                        per_t.append(jstats.module_stats_masked(
                            disc, sub_c, sub_n, zd,
                            n_iter=cfg.power_iters,
                            summary_method=cfg.summary_method,
                        ))
                    outs.append(jnp.stack(per_t))        # (T, C, K, 7)
                elif uniform:
                    over_test = jax.vmap(
                        over_perm,
                        in_axes=(None, None, 0, None if tn_absent else 0,
                                 None if td_absent else 0),
                    )
                    outs.append(over_test(disc, idx_b, tc, tn, td))  # (T,C,K,7)
                else:
                    outs.append(jnp.stack([
                        over_perm(disc, idx_b, tc[t],
                                  None if tn_absent else tn[t], td[t])
                        for t in range(T)
                    ]))
            return outs

        return chunk, chunk_args, False

    def _fingerprint_extra(self) -> bytes:
        """Checkpoint identity of the test side — digested from the HOST
        inputs at construction, so it is identical on every mesh shape
        and sharding mode (the elastic-resume contract, ISSUE 6)."""
        return f"|T:{self.T}|td:{self._host_test_digest}".encode()

    def _null_write(self, profile=None) -> Callable:
        """Chunk→null scatter shared by the fixed and adaptive loops (reads
        the base engine's buckets at call time — see
        :meth:`PermutationEngine._null_write`)."""

        def write(nulls, outs, done, take):
            from .distributed import gather_to_host
            from .engine import _trim_tail_shards

            for b, outarr in zip(self._base.buckets, outs):
                # full-chunk transfer, host-side slice (device slicing is an
                # eager op — ~1s dispatch on tunneled backends); a single
                # advanced index (module_pos) keeps its axis position in the
                # assignment target. On multi-host meshes only,
                # _trim_tail_shards drops whole trailing perm-axis (dim 1)
                # shards of a tail chunk before the cross-host allgather.
                arr = gather_to_host(
                    _trim_tail_shards(outarr, take, axis=1)
                ).astype(np.float64)
                if profile is not None:
                    profile.record_transfer(arr.nbytes)
                nulls[:, done: done + take, b.module_pos] = arr[:, :take]

        return write

    def rebucket(self, active) -> None:
        """Shrink to the surviving module subset (adaptive retirement) —
        delegates the bucket rebuild to the base engine (original
        permutation-slice offsets preserved) and invalidates this wrapper's
        jitted chunk."""
        self._base.rebucket(active)
        self._chunk_cached = None
        self._stream_cached = {}

    def run_null(self, n_perm: int, key=0, progress=None,
                 nulls_init=None, start_perm: int = 0,
                 checkpoint_path: str | None = None,
                 checkpoint_every: int = 8192, profile=None,
                 telemetry=None, fault_policy=None, observed=None):
        """(T, n_perm, n_modules, 7) null array + completed count; same
        chunked/interruptible/reproducible/resumable/checkpointable contract
        as the base engine (key derivation and chunk rounding are shared
        helpers on :class:`PermutationEngine` so the two paths cannot
        drift). ``observed`` is accepted for signature parity with the base
        engine and unused: the T-axis programs always run f32 (__init__
        refuses an explicit bf16_rescue ask)."""
        del observed
        from .engine import _telemetry_profile, run_checkpointed_chunks

        # resolve before building the write closure so an auto-created
        # NullProfile is the instance `write` records transfer bytes to
        telemetry, profile = _telemetry_profile(telemetry, profile)
        return run_checkpointed_chunks(
            self._base, n_perm, key, self._chunk_fn(),
            (self.T, n_perm, self.n_modules, N_STATS),
            self._null_write(profile),
            progress=progress, nulls_init=nulls_init, start_perm=start_perm,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
            perm_axis=1, profile=profile, telemetry=telemetry,
            fault_policy=fault_policy,
            # the test-side matrices live on this wrapper (the base engine is
            # discovery-only), so their content digest rides fingerprint_extra
            fingerprint_extra=self._fingerprint_extra(),
        )

    def run_null_adaptive(self, n_perm: int, observed, key=0,
                          alternative: str = "greater", rule=None,
                          progress=None,
                          checkpoint_path: str | None = None,
                          checkpoint_every: int = 8192, telemetry=None,
                          fault_policy=None):
        """Sequential early-stopping variant of :meth:`run_null`
        (:meth:`PermutationEngine.run_null_adaptive` semantics). A module
        retires only when its decision is settled in EVERY test dataset:
        the ``(T, n_modules, 7)`` observed statistics fold into the
        monitor's cell axis as ``(n_modules, T*7)``, so each (dataset,
        statistic) cell is tallied independently and the shared permutation
        draw still serves all T cohorts of the surviving modules."""
        from ..ops.sequential import StopMonitor, StopRule

        obs = np.asarray(observed, dtype=np.float64)
        monitor = StopMonitor(
            np.moveaxis(obs, 0, 1).reshape(self.n_modules, -1),
            alternative, rule or StopRule(),
        )
        return self.run_null_monitored(
            n_perm, key, monitor, progress=progress,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, telemetry=telemetry,
            fault_policy=fault_policy,
        )

    def run_null_monitored(self, n_perm: int, key, monitor, progress=None,
                           checkpoint_path: str | None = None,
                           checkpoint_every: int = 8192, telemetry=None,
                           fault_policy=None):
        """T-axis packed-run entry point (ISSUE 7) — the multi-test twin of
        :meth:`PermutationEngine.run_null_monitored`: a chunked null under
        a caller-supplied retirement monitor whose cell axis folds the T
        datasets in as ``(n_modules, T*7)``. The serve scheduler drives
        multi-test requests through this with its ceiling/SLO monitor, so
        a request analyzing one discovery against several cohorts rides
        ONE shared permutation draw per chunk (the vmap_tests contract)
        while still exiting early through retirement re-bucketing."""
        from .engine import run_adaptive_chunks

        def slice_vals(nulls, done, take, pos):
            block = nulls[:, done: done + take][:, :, pos, :]
            # (T, take, P, 7) -> (take, P, T*7): dataset axis joins stats
            return np.moveaxis(block, 0, 2).reshape(take, pos.size, -1)

        try:
            return run_adaptive_chunks(
                self._base, n_perm, key, self._chunk_fn,
                (self.T, n_perm, self.n_modules, N_STATS),
                self._null_write(), slice_vals, monitor, self.rebucket,
                progress=progress, checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every, perm_axis=1,
                fingerprint_extra=self._fingerprint_extra(),
                telemetry=telemetry, fault_policy=fault_policy,
            )
        finally:
            self.rebucket(range(self.n_modules))

    # ------------------------------------------------------------------
    # Streaming tallies (store_nulls=False) — superchunk executor
    # ------------------------------------------------------------------

    def _obs_buckets(self, observed) -> list:
        """Per-bucket (T, K_b, 7) observed statistics as device f32
        operands of the streaming count programs (the f64→f32 cast is
        exact for engine-computed statistics — see
        :meth:`PermutationEngine._obs_buckets`)."""
        import jax.numpy as jnp

        obs = np.asarray(observed, dtype=np.float64).reshape(
            self.T, self.n_modules, N_STATS
        )
        return [
            jnp.asarray(obs[:, b.module_pos], jnp.float32)
            for b in self._base.buckets
        ]

    def _stream_program(self, observed, adaptive: bool):
        """Cached :meth:`_build_stream_program` — a fresh closure per run
        would re-trace/re-compile the whole program every call."""
        key = (bool(adaptive),
               np.asarray(observed, dtype=np.float64).tobytes())
        if key not in self._stream_cached:
            self._stream_cached[key] = self._build_stream_program(
                observed, adaptive
            )
        return self._stream_cached[key]

    def _build_stream_program(self, observed, adaptive: bool):
        """Jit a streaming program with the multi-test axis layout
        (outputs ``(T, C, K_b, 7)`` → counts reduce perm axis 1) and the
        same mesh composition as :meth:`_finish_chunk`. ``adaptive=False``
        returns the superchunk scan ``fn(tallies, keys, valid)``;
        ``adaptive=True`` the per-chunk count ``fn(keys, valid)``."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .engine import (
            _globalize_replicated, build_stream_super, chunk_count_deltas,
            make_count_buckets,
        )

        cfg = self.config
        if self.stat_mode == "fused":
            # mega-kernel counter: the tally fold happens in VMEM
            # (ISSUE 8); the program still needs the whole-chunk shard_map
            # on a mesh (pallas_call cannot be auto-partitioned) with the
            # per-shard deltas psum'd inside the counter
            _, args, _ = self._chunk_parts()
            shard = self.mesh is not None
            axis = cfg.mesh_axis if shard else None
            count_chunk = self._fused_count_chunk(axis)
            if adaptive:
                program = count_chunk
            else:
                program = build_stream_super(
                    None, None, count_chunk=count_chunk
                )
        else:
            chunk, args, fused_rep = self._chunk_parts()
            shard = fused_rep and self.mesh is not None
            axis = cfg.mesh_axis if shard else None
            count_buckets = make_count_buckets(1)
            if adaptive:
                def program(keys, valid, chunk_ops, obs_b):
                    return chunk_count_deltas(
                        chunk, count_buckets, axis, keys, valid, chunk_ops,
                        obs_b,
                    )
            else:
                program = build_stream_super(chunk, count_buckets, axis)
        obs = self._obs_buckets(observed)
        if adaptive:
            keys_spec = P(cfg.mesh_axis)
            donate = ()
        else:
            keys_spec = P(None, cfg.mesh_axis)
            # no carry donation on the fused path (see
            # PermutationEngine._build_stream_super: tiny tallies, and
            # donation into interpret-mode pallas state machinery proved
            # alias-unsafe on XLA:CPU)
            donate = () if self.stat_mode == "fused" else (0,)
        if self.mesh is not None:
            from .distributed import to_global

            ksh = NamedSharding(self.mesh, keys_spec)
            if shard:
                from .sharded import _NO_CHECK_KW, _shard_map

                head = () if adaptive else (P(),)
                program = _shard_map(
                    program,
                    mesh=self.mesh,
                    in_specs=head + (keys_spec, P(), P(), P()),
                    out_specs=P(),
                    **_NO_CHECK_KW,
                )
            jitted = jax.jit(program, donate_argnums=donate)
            args, obs = _globalize_replicated(self.mesh, (args, obs))
            if adaptive:
                return lambda keys, valid: jitted(
                    to_global(keys, ksh), valid, args, obs
                )
            return lambda tallies, keys, valid: jitted(
                tallies, to_global(keys, ksh), valid, args, obs
            )
        jitted = jax.jit(program, donate_argnums=donate)
        if adaptive:
            return lambda keys, valid: jitted(keys, valid, args, obs)
        return lambda tallies, keys, valid: jitted(
            tallies, keys, valid, args, obs
        )

    def _stream_tallies_init(self, host=None) -> list:
        """Per-bucket (T, K_b, 7) int32 tally carry (zeros or restored
        from a checkpoint's (T, n_modules, 7) host tallies)."""
        import jax.numpy as jnp

        from .engine import _globalize_replicated

        out = []
        for b in self._base.buckets:
            if host is None:
                vals = [
                    np.zeros((self.T, len(b.module_pos), N_STATS), np.int32)
                    for _ in range(3)
                ]
            else:
                vals = [
                    np.asarray(a)[:, b.module_pos].astype(np.int32)
                    for a in host
                ]
            out.append(tuple(jnp.asarray(v) for v in vals))
        if self.mesh is not None:
            out = _globalize_replicated(self.mesh, out)
        return out

    def _stream_tallies_pull(self, tallies) -> tuple:
        """Device tallies → global ``(T, n_modules, 7)`` int64 arrays."""
        from .distributed import gather_to_host

        shape = (self.T, self.n_modules, N_STATS)
        hi = np.zeros(shape, np.int64)
        lo = np.zeros_like(hi)
        eff = np.zeros_like(hi)
        for b, (h, l, e) in zip(self._base.buckets, tallies):
            hi[:, b.module_pos] = gather_to_host(h)
            lo[:, b.module_pos] = gather_to_host(l)
            eff[:, b.module_pos] = gather_to_host(e)
        return hi, lo, eff

    def _counts_to_active(self, outs, pos) -> tuple:
        """Adaptive streaming: (T, K_b, 7) count deltas → ``(n_active,
        T*7)`` host arrays in the monitor's cell layout (dataset axis
        folded into the statistic axis, matching ``run_null_adaptive``'s
        ``slice_vals`` convention)."""
        hi, lo, eff = self._stream_tallies_pull(outs)

        def to_cells(a):
            return np.moveaxis(a[:, pos], 0, 1).reshape(pos.size, -1)

        return to_cells(hi), to_cells(lo), to_cells(eff)

    def run_null_streaming(self, n_perm: int, observed, key=0,
                           progress=None,
                           checkpoint_path: str | None = None,
                           checkpoint_every: int = 8192, profile=None,
                           telemetry=None, fault_policy=None):
        """Streaming-mode (``store_nulls=False``) variant of
        :meth:`run_null` — the superchunk executor over the shared
        permutation draw, tallying every (dataset, module, statistic) cell
        on device (see :meth:`PermutationEngine.run_null_streaming`).
        Returns a :class:`~netrep_tpu.parallel.engine.StreamCounts` with
        ``(T, n_modules, 7)`` tallies."""
        from ..utils.autotune import resolve_superchunk
        from .engine import run_stream_superchunks

        base = self._base
        sk_key = base.autotune_key(extra=f"T{self.T}|superchunk")
        K, cache = resolve_superchunk(self.config, sk_key)
        base._stream_autotune_record = (
            (cache, sk_key, K) if cache is not None else None
        )
        return run_stream_superchunks(
            base, n_perm, key, self._stream_program(observed, False),
            K, base.effective_chunk(),
            self._stream_tallies_init, self._stream_tallies_pull,
            progress=progress, checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every,
            fingerprint_extra=self._fingerprint_extra(), profile=profile,
            telemetry=telemetry, fault_policy=fault_policy,
        )

    def run_null_adaptive_streaming(self, n_perm: int, observed, key=0,
                                    alternative: str = "greater", rule=None,
                                    progress=None,
                                    checkpoint_path: str | None = None,
                                    checkpoint_every: int = 8192,
                                    profile=None, telemetry=None,
                                    fault_policy=None):
        """Streaming-mode variant of :meth:`run_null_adaptive`: the
        monitor folds device-computed (dataset × statistic) counts
        directly, with retirement decisions bit-identical to the
        materialized adaptive run at the same key (see
        :meth:`PermutationEngine.run_null_adaptive_streaming`). Returns a
        :class:`~netrep_tpu.parallel.engine.StreamCounts` with
        ``(T, n_modules, 7)`` tallies and per-module ``n_perm_used``."""
        from ..ops.sequential import StopMonitor, StopRule
        from .engine import StreamCounts, run_adaptive_stream_chunks

        obs = np.asarray(observed, dtype=np.float64)
        monitor = StopMonitor(
            np.moveaxis(obs, 0, 1).reshape(self.n_modules, -1),
            alternative, rule or StopRule(),
        )
        try:
            monitor, completed, finished = run_adaptive_stream_chunks(
                self._base, n_perm, key,
                lambda: self._stream_program(observed, True),
                self._counts_to_active, monitor, self.rebucket,
                progress=progress, checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every,
                fingerprint_extra=self._fingerprint_extra(),
                profile=profile, telemetry=telemetry,
                fault_policy=fault_policy,
            )
        finally:
            self.rebucket(range(self.n_modules))

        def to_result(a):
            # (n_modules, T*7) monitor cells -> (T, n_modules, 7)
            return np.moveaxis(
                np.asarray(a).reshape(self.n_modules, self.T, N_STATS), 0, 1
            ).copy()

        eff = monitor.eff if monitor.eff is not None else np.zeros_like(
            monitor.hi
        )
        return StreamCounts(
            hi=to_result(monitor.hi), lo=to_result(monitor.lo),
            eff=to_result(eff), completed=completed,
            n_perm_used=monitor.n_used.copy(), finished=finished,
        )
