"""Mixed-precision null screening (ISSUE 16): bf16 fast pass with exact
f32 rescue.

The null permutation loop is where essentially all device time goes, yet
almost no permutation's statistics land anywhere near the observed value —
only near-threshold exceedance comparisons need full precision. The
screened loop therefore runs each chunk through the EXISTING chunk body
with the test-side operands rounded through bfloat16 in-program (f32
arithmetic on bf16-rounded inputs: on TPU the MXU consumes the bf16
operands natively at ~2x the f32 rate and half the gather/DMA bytes; on
CPU the same rounding is emulated exactly, which is what makes the tier-1
pinning tests meaningful). A per-(module, statistic) forward-error
cushion — derived the same way :func:`netrep_tpu.atlas.builder._bound_margin`
bounds the atlas tile pass — then splits every exceedance comparison into:

- **decided**: the screened value clears ``observed`` by more than the
  cushion. The f32 value provably falls on the same side of ``observed``,
  so the ``>=`` / ``<=`` tallies are taken from the screened value as-is.
- **ambiguous**: the screened value lands inside the cushion band. The
  whole permutation joins a worklist that is re-dispatched through the
  engine's existing f32 chunk program (same compiled executable, same
  per-permutation keys), and its exact values replace the screened ones.

Counts, p-values, and adaptive retirement decisions are therefore
bit-identical to the all-f32 path BY CONSTRUCTION — the cushion only
moves work between the fast pass and the rescue dispatch, never the
result. Two structural caveats are accepted and documented (
docs/architecture.md "Mixed-precision null screening"): NaN-ness of a
statistic is assumed precision-invariant (a statistic that is NaN in f32
is NaN under bf16-rounded inputs and vice versa — NaNs here come from
empty masks and zero variances, which rounding does not create), and a
cell whose OBSERVED value is NaN never tallies under any precision, so
it is never rescued.

Cushion derivation. For each statistic the screened value differs from
the f32 value by a forward error bounded (to first order) by the bf16
unit roundoff ``2**-9`` scaled by the operand amplitude and the
statistic's own magnitude near the decision boundary — where the
screened value is within the cushion of ``observed``, its magnitude is
``~|observed|``. So, mirroring ``_bound_margin``'s shape
(``scale * unit * amplitude + absolute_floor``):

    cushion[m, s] = margin_scale * 2**-9 * A_op * max(1, |observed[m, s]|)
                    + 1e-6

with ``A_op = max(1, max|test operands|)`` folding the absolute error of
accumulation over rounded inputs, and ``margin_scale`` (default 32, env
override ``NETREP_NULL_MARGIN_SCALE``) the headroom multiplier for the
condition of the seven statistic pipelines (power iteration, means,
correlations of gathered blocks). The cushion is deliberately
conservative: overestimating it only inflates the rescued fraction (more
exact f32 work), never the counts.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

#: bfloat16 unit roundoff (8-bit significand).
BF16_UNIT = 2.0 ** -9

#: headroom multiplier over the first-order forward-error bound — the
#: mixed-precision analogue of the 16x factor in
#: :func:`netrep_tpu.atlas.builder._bound_margin`.
DEFAULT_MARGIN_SCALE = 32.0

#: absolute cushion floor (same role as ``_bound_margin``'s ``1e-7``,
#: one decade wider for the coarser bf16 unit).
CUSHION_FLOOR = 1e-6

#: checkpoint-fingerprint suffix: a screened run's nulls carry bf16
#: values in decided rows, so its checkpoints must never resume an
#: all-f32 run (or vice versa) — counts agree, stored values don't.
SCREEN_FP = b"null-precision:bf16_rescue|"


def resolve_margin_scale() -> float:
    """``margin_scale``, honouring the ``NETREP_NULL_MARGIN_SCALE`` env
    override (an escape hatch for pinning-test triage: widening the
    cushion trades rescue volume for certainty, it cannot change
    results)."""
    raw = os.environ.get("NETREP_NULL_MARGIN_SCALE", "")
    try:
        val = float(raw)
    except ValueError:
        return DEFAULT_MARGIN_SCALE
    return val if val > 0 else DEFAULT_MARGIN_SCALE


def bf16_round(x):
    """Round an f32 operand through bfloat16 (``None`` passes through —
    the data-only chunk body takes ``tc=tn=None``). Stays inside the
    jitted program so the screened chunk body IS the existing chunk body
    on rounded inputs — no second statistics implementation to keep in
    sync."""
    if x is None:
        return None
    return x.astype(jnp.bfloat16).astype(jnp.float32)


def null_cushions(
    observed: np.ndarray,
    operand_amp: float,
    margin_scale: float | None = None,
) -> np.ndarray:
    """Per-(module, statistic) decision cushion (f32, same shape as
    ``observed``). NaN observed cells get NaN cushions — they never
    tally under any precision (every comparison against NaN is False),
    so they are excluded from the ambiguity test rather than rescued."""
    if margin_scale is None:
        margin_scale = resolve_margin_scale()
    obs = np.asarray(observed, dtype=np.float64)
    amp = max(1.0, float(operand_amp))
    cush = (
        margin_scale * BF16_UNIT * amp * np.maximum(1.0, np.abs(obs))
        + CUSHION_FLOOR
    )
    return cush.astype(np.float32)


def ambiguous_cells(out, obs, cush):
    """Inside-jit ambiguity test for one bucket: ``out`` is the screened
    ``(..., K_b, N_STATS)`` chunk output, ``obs``/``cush`` the bucket's
    ``(K_b, N_STATS)`` observed values and cushions. A cell is DECIDED
    when the screened value clears the cushion band on either side, when
    both the screened value and the observed value are NaN (neither
    precision tallies, eff agrees by the NaN-invariance assumption), or
    when the observed value is NaN (the cell never tallies at all).
    Everything else is ambiguous."""
    dec_hi = out > obs + cush
    dec_lo = out < obs - cush
    both_nan = jnp.isnan(out) & jnp.isnan(obs)
    decided = dec_hi | dec_lo | both_nan | jnp.isnan(obs)
    return ~decided


def ambiguous_perms(outs, obs_b, cush_b):
    """OR :func:`ambiguous_cells` over every bucket and every (module,
    statistic) cell → per-permutation ``(C,)`` bool worklist mask. One
    ambiguous cell rescues the whole permutation: the rescue re-runs the
    full f32 chunk body anyway, and whole-row replacement keeps the
    stored nulls bit-identical to the f32 run for every rescued row."""
    amb = None
    for o, ob, cb in zip(outs, obs_b, cush_b):
        a = ambiguous_cells(o, ob, cb).any(axis=(1, 2))
        amb = a if amb is None else amb | a
    return amb


def take_keys(keys, idx: np.ndarray):
    """Row-gather of a per-permutation PRNG key array by host indices
    (typed key arrays don't always support ``jnp.take`` directly — fall
    back to a key-data round-trip, which is layout-exact)."""
    idx = jnp.asarray(np.asarray(idx, dtype=np.int64))
    try:
        return jnp.take(keys, idx, axis=0)
    except (TypeError, ValueError):
        data = jax.random.key_data(keys)
        return jax.random.wrap_key_data(jnp.take(data, idx, axis=0))


def pad_worklist(idx: np.ndarray, chunk: int) -> np.ndarray:
    """Pad a rescued-permutation index list up to the chunk size (the f32
    rescue reuses the engine's chunk program, whose key axis is the fixed
    chunk length — padding repeats the first worklist entry, and the
    padded rows' outputs are dropped)."""
    idx = np.asarray(idx, dtype=np.int64)
    pad = np.full(chunk - idx.size, idx[0], dtype=np.int64)
    return np.concatenate([idx, pad])


def host_tail_counts(vals: np.ndarray, obs: np.ndarray):
    """Exact (hi, lo, eff) exceedance tallies for rescued permutations,
    computed on the host: ``vals`` is ``(R, K_b, N_STATS)`` f32 from the
    f32 rescue dispatch, ``obs`` the bucket's ``(K_b, N_STATS)`` f64
    observed values. Comparisons are made at f64 after an exact f32
    widen, which decides identically to the device's f32-vs-f32
    compares (the engine stores observed as an exact f64→f32 cast; see
    ``PermutationEngine._obs_buckets``)."""
    v = np.asarray(vals, dtype=np.float64)
    ob = (
        np.asarray(obs, dtype=np.float64)[None]
        .astype(np.float32)
        .astype(np.float64)
    )
    with np.errstate(invalid="ignore"):
        hi = (v >= ob).sum(axis=0).astype(np.int64)
        lo = (v <= ob).sum(axis=0).astype(np.int64)
    eff = (~np.isnan(v)).sum(axis=0).astype(np.int64)
    return hi, lo, eff


class RescueState:
    """Running tally of the screened pass — how many permutations went
    through the screen, how many fell in the ambiguity band and were
    re-dispatched in f32, and in how many rescue dispatches. Rides the
    null-loop checkpoints via the loops' ``extra_state`` hook so a
    resumed run reports the whole run's rescued fraction, not the
    post-resume remainder."""

    def __init__(self):
        self.total = 0
        self.rescued = 0
        self.dispatches = 0

    def fraction(self) -> float:
        return self.rescued / self.total if self.total else 0.0

    def state_arrays(self) -> dict:
        return {
            "screen_total": np.int64(self.total),
            "screen_rescued": np.int64(self.rescued),
            "screen_dispatches": np.int64(self.dispatches),
        }

    def restore_state(self, extras: dict) -> None:
        self.total = int(np.asarray(extras.get("screen_total", 0)))
        self.rescued = int(np.asarray(extras.get("screen_rescued", 0)))
        self.dispatches = int(
            np.asarray(extras.get("screen_dispatches", 0))
        )
