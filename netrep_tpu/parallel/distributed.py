"""Multi-host runtime initialization — the cross-host half of the
distributed communication backend (SURVEY.md §2.3 last row, §5 "Distributed
communication backend"): the reference is a single shared-memory process
(OpenMP [B:5], no MPI/NCCL); at TPU-pod scale the equivalent is one JAX
process per host joined through the coordination service, with a global
``jax.sharding.Mesh`` whose ``perm`` axis spans hosts (collectives ride ICI
within a slice, DCN across hosts — :mod:`netrep_tpu.parallel.mesh`).

Usage on each host (identical SPMD program, reference-style API untouched)::

    from netrep_tpu.parallel import distributed, mesh
    distributed.initialize()            # env-driven; no-op single-host
    m = mesh.make_mesh()                # jax.devices() now spans all hosts
    module_preservation(..., mesh=m)

The permutation engine gathers each host's shard of the null distribution
with ``process_allgather`` (:mod:`netrep_tpu.parallel.engine`), so every
process returns the full result — matching the reference's single-process
semantics from the user's point of view.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger("netrep_tpu")

#: Environment variables consulted when arguments are omitted (the standard
#: JAX coordination-service contract; also auto-detected on Cloud TPU VMs,
#: where jax.distributed.initialize() needs no arguments at all).
ENV_VARS = {
    "coordinator_address": "JAX_COORDINATOR_ADDRESS",
    "num_processes": "JAX_NUM_PROCESSES",
    "process_id": "JAX_PROCESS_ID",
}


def is_initialized() -> bool:
    """Whether the multi-host runtime is up (single-process runs: False)."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    # older jax without the public predicate: consult the global state the
    # initialize() call populates
    state = getattr(
        getattr(jax._src, "distributed", None), "global_state", None
    )
    return getattr(state, "client", None) is not None


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> dict:
    """Join the JAX coordination service — idempotent, env-var driven.

    Arguments default from ``ENV_VARS``; on Cloud TPU VMs all three may be
    omitted (JAX auto-detects the pod topology). Calling again after a
    successful join is a no-op (the reference has no analogous step — its
    "backend" is process-local threads — so this function is deliberately
    safe to call unconditionally at program start).

    Returns a summary dict: ``process_id``, ``process_count``,
    ``local_device_count``, ``global_device_count``.
    """
    if not is_initialized():
        coordinator_address = coordinator_address or os.environ.get(
            ENV_VARS["coordinator_address"]
        )
        if num_processes is None and ENV_VARS["num_processes"] in os.environ:
            num_processes = int(os.environ[ENV_VARS["num_processes"]])
        if process_id is None and ENV_VARS["process_id"] in os.environ:
            process_id = int(os.environ[ENV_VARS["process_id"]])
        given = (coordinator_address, num_processes, process_id)
        if any(v is not None for v in given) and any(v is None for v in given):
            raise ValueError(
                "partial multi-host configuration: coordinator_address, "
                "num_processes and process_id must be given (or set via "
                f"{sorted(ENV_VARS.values())}) together, got "
                f"address={coordinator_address!r} num={num_processes!r} "
                f"id={process_id!r}. On Cloud TPU VMs omit all three."
            )
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except Exception as exc:
            if any(v is not None for v in given):
                raise  # explicit configuration that failed — surface it
            # No configuration given and the auto-detect join failed — on a
            # plain single machine that is the expected "no cluster" case,
            # but on a real pod it could be a transient coordinator failure
            # whose silent fallback would hang the OTHER hosts at their
            # first collective. Log loudly enough to diagnose that.
            logger.warning(
                "multi-host auto-detection did not join a coordination "
                "service (%s: %s); continuing single-process. If this host "
                "IS part of a pod, other hosts will hang — set "
                "%s/%s/%s explicitly.",
                type(exc).__name__, exc, *sorted(ENV_VARS.values()),
                exc_info=logger.isEnabledFor(logging.DEBUG),
            )
            from ..utils.telemetry import current as _tel

            tel = _tel()
            if tel is not None:
                # the crash-safe JSONL keeps the "other hosts will hang"
                # precondition diagnosable offline — the warning above
                # scrolls away, the event does not
                tel.emit(
                    "distributed_autodetect_failed",
                    error=type(exc).__name__, detail=str(exc)[:200],
                )
        else:
            logger.info(
                "joined coordination service: process %d/%d, %d local "
                "device(s)", jax.process_index(), jax.process_count(),
                jax.local_device_count(),
            )
            from ..utils.telemetry import current as _tel

            tel = _tel()
            if tel is not None:
                tel.emit(
                    "distributed_init",
                    process_id=jax.process_index(),
                    process_count=jax.process_count(),
                    local_devices=jax.local_device_count(),
                    global_devices=jax.device_count(),
                )
    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def filter_addressable(devices) -> list:
    """Keep only devices the runtime can still enumerate — the multi-host
    guard of the elastic shrink rung (ISSUE 6): after a HOST loss, the
    dead host's devices may still appear in a survivor candidate list
    derived from the old mesh, but ``jax.devices()`` no longer returns
    them; building the shrunken mesh over a phantom device would fail at
    its first collective instead of here. Single-process (and the CPU
    drill harness): an identity filter — every mesh device is live.
    Returns ``[]`` when the runtime itself can no longer enumerate
    devices (the whole client is gone; the caller takes the CPU rung)."""
    try:
        alive = set(jax.devices())
    except RuntimeError:
        return []
    return [d for d in devices if d in alive]


def to_global(x, sharding):
    """Place a host-local array onto ``sharding``. Single-process (fully
    addressable): a plain ``device_put``. Multi-host: ``device_put`` rejects
    non-addressable shardings, so assemble the global array from each
    process's addressable shards — valid because the engine's SPMD contract
    has every process compute the identical host-local value (keys from the
    same seed, replicated matrices)."""
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx: x[idx]
    )


def gather_to_host(x):
    """Return ``x`` as a host-local numpy array on every process.

    Single-process (the common case): a plain transfer. Multi-host: the
    array's shards live on other hosts' devices, so a ``process_allgather``
    assembles the global value first — this is the cross-host hop of the
    null-distribution collection (engine ``write`` path).
    """
    import numpy as np

    # Key on the ARRAY's addressability, not process_count: in a multi-host
    # program an engine run without the global mesh yields fully-addressable
    # outputs, for which process_allgather would take its host-local branch
    # and concatenate copies across processes instead of replicating.
    if not getattr(x, "is_fully_addressable", True):
        import time

        from jax.experimental import multihost_utils

        from ..utils.telemetry import current as _tel

        t0 = time.perf_counter()
        x = multihost_utils.process_allgather(x, tiled=True)
        tel = _tel()
        if tel is not None:
            # the cross-host DCN hop of null collection: per-allgather
            # timing makes a slow host / sick DCN link visible per event
            # instead of only in the run's total (ISSUE 3)
            tel.emit("allgather", s=time.perf_counter() - t0,
                     bytes=int(getattr(x, "nbytes", 0)))
    return np.asarray(x)
