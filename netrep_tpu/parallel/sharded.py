"""Row-sharded n×n matrices with collective-assembled module gathers —
the framework's "context parallelism" (SURVEY.md §5 "long-context": the role
of the long axis is played by network size n; at 50k nodes the three n×n f32
matrices are ~10 GB each and must be sharded across the mesh, with module
submatrix gathers assembled by collectives; §7 step 5, Config D
[BASELINE.json:10]).

Design: a matrix is laid out ``P(ROW_AXIS, None)`` — each device owns a
contiguous block of rows (full row width, so the column gather is local).
A module gather ``M[idx][:, idx]`` becomes, inside ``shard_map``:

1. local column gather ``block[:, idx]`` — (rows/D, m), pure local HBM reads;
2. local row selection: positions of ``idx`` that fall inside this device's
   row block, others zeroed;
3. ``psum`` over the row axis — each shard contributes its disjoint rows, the
   sum assembles the full (m, m) submatrix on every shard.

The psum rides ICI and moves only O(m²) per gather — m ≪ n, so the collective
is tiny compared to the HBM savings of never materializing n² on one device.

Data matrices (samples × n, samples ≪ n) stay replicated and are gathered
with a plain ``take`` outside the shard region.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import ROW_AXIS

try:  # jax ≥ 0.6 exports shard_map at top level; older under experimental
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_rows(mat, mesh: Mesh, axis: str = ROW_AXIS):
    """Place an (n, n) matrix with rows sharded over ``axis``. Rows must
    divide evenly by the axis size (pad first: :func:`pad_rows_to_multiple`)."""
    n = mat.shape[0]
    d = mesh.shape[axis]
    if n % d:
        raise ValueError(
            f"rows ({n}) not divisible by mesh axis {axis!r} size {d}; "
            "pad the matrix first (pad_rows_to_multiple)"
        )
    return jax.device_put(mat, NamedSharding(mesh, P(axis, None)))


def pad_square_to_multiple(mat, d: int):
    """Zero-pad both axes of a square matrix to a multiple of ``d`` (padding
    is inert: gather indices only ever point at real nodes)."""
    import numpy as np

    n = mat.shape[0]
    pad = (-n) % d
    if pad == 0:
        return mat
    return np.pad(np.asarray(mat), [(0, pad), (0, pad)])


def gather_submatrix_local(block: jnp.ndarray, idx: jnp.ndarray, axis: str = ROW_AXIS):
    """Inside ``shard_map``: assemble ``M[idx][:, idx]`` from this device's
    row block via the local-gather + psum recipe (module docstring).

    ``block`` is (rows_per_shard, n); ``idx`` is (m,) global row/col indices,
    replicated across the row axis. Returns the full (m, m) submatrix
    (identical on every row shard after the psum)."""
    rows_per = block.shape[0]
    start = jax.lax.axis_index(axis) * rows_per
    rel = idx - start
    in_block = (rel >= 0) & (rel < rows_per)
    safe = jnp.where(in_block, rel, 0)
    cols = block[:, idx]                       # (rows_per, m) local gather
    part = jnp.where(in_block[:, None], cols[safe, :], 0.0)  # (m, m)
    return jax.lax.psum(part, axis)


def make_sharded_gatherer(mesh: Mesh, batch_axis: str | None = None):
    """Build a ``shard_map``-wrapped batched gather over row-sharded
    correlation/network matrices.

    Returns ``gather(corr, net, idx)`` with ``idx`` (..., m) int32
    (arbitrary leading batch dims) → ``(sub_corr, sub_net)`` each
    (..., m, m). With ``batch_axis`` set (e.g. the permutation axis), the
    leading batch dim of ``idx`` and of the outputs stays sharded over that
    mesh axis — permutation data parallelism composes with row sharding on a
    2-D mesh, and each psum assembles only the local permutation shard's
    submatrices. The psums batch into one collective pair per call."""

    def body(corr_blk, net_blk, idx_rep):
        def one(ix):
            return (
                gather_submatrix_local(corr_blk, ix),
                gather_submatrix_local(net_blk, ix),
            )

        fn = one
        for _ in range(idx_rep.ndim - 1):
            fn = jax.vmap(fn)
        return fn(idx_rep)

    idx_spec = P(batch_axis) if batch_axis else P()

    def gather(corr, net, idx):
        return _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(ROW_AXIS, None), P(ROW_AXIS, None), idx_spec),
            out_specs=(idx_spec, idx_spec),
        )(corr, net, idx)

    return gather
