"""Row-sharded n×n matrices with collective-assembled module gathers —
the framework's "context parallelism" (SURVEY.md §5 "long-context": the role
of the long axis is played by network size n; at 50k nodes the three n×n f32
matrices are ~10 GB each and must be sharded across the mesh, with module
submatrix gathers assembled by collectives; §7 step 5, Config D
[BASELINE.json:10]).

Design: a matrix is laid out ``P(ROW_AXIS, None)`` — each device owns a
contiguous block of rows (full row width, so the column gather is local).
A module gather ``M[idx][:, idx]`` becomes, inside ``shard_map``:

1. local column gather ``block[:, idx]`` — (rows/D, m), pure local HBM reads;
2. local row selection: positions of ``idx`` that fall inside this device's
   row block, others zeroed;
3. ``psum`` over the row axis — each shard contributes its disjoint rows, the
   sum assembles the full (m, m) submatrix on every shard.

The psum rides ICI and moves only O(m²) per gather — m ≪ n, so the collective
is tiny compared to the HBM savings of never materializing n² on one device.

Data matrices (samples × n, samples ≪ n) stay replicated and are gathered
with a plain ``take`` outside the shard region.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import ROW_AXIS

try:  # jax ≥ 0.6 exports shard_map at top level; older under experimental
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication/vma checker kwarg is version-dependent (check_vma on
# jax ≥ 0.7, check_rep before); the fused mode must disable it because
# pallas_call outputs carry no varying-axes annotation
import inspect as _inspect

_SM_PARAMS = _inspect.signature(_shard_map).parameters
_NO_CHECK_KW = (
    {"check_vma": False} if "check_vma" in _SM_PARAMS
    else {"check_rep": False} if "check_rep" in _SM_PARAMS
    else {}
)


def shard_rows(mat, mesh: Mesh, axis: str = ROW_AXIS):
    """Place an (n, n) matrix with rows sharded over ``axis``. Rows must
    divide evenly by the axis size (pad first: :func:`pad_rows_to_multiple`)."""
    n = mat.shape[0]
    d = mesh.shape[axis]
    if n % d:
        raise ValueError(
            f"rows ({n}) not divisible by mesh axis {axis!r} size {d}; "
            "pad the matrix first (pad_rows_to_multiple)"
        )
    from .distributed import to_global

    # to_global == device_put single-process; on multi-host meshes it
    # assembles the global array from each process's addressable shards
    # (device_put rejects non-addressable shardings)
    return to_global(mat, NamedSharding(mesh, P(axis, None)))


def pad_square_to_multiple(mat, d: int):
    """Zero-pad both axes of a square matrix to a multiple of ``d`` (padding
    is inert: gather indices only ever point at real nodes)."""
    import numpy as np

    n = mat.shape[0]
    pad = (-n) % d
    if pad == 0:
        return mat
    return np.pad(np.asarray(mat), [(0, pad), (0, pad)])


def gather_submatrix_local(block: jnp.ndarray, idx: jnp.ndarray, axis: str = ROW_AXIS):
    """Inside ``shard_map``: assemble ``M[idx][:, idx]`` from this device's
    row block via the local-gather + psum recipe (module docstring).

    ``block`` is (rows_per_shard, n); ``idx`` is (m,) global row/col indices,
    replicated across the row axis. Returns the full (m, m) submatrix
    (identical on every row shard after the psum).

    This is the *direct* (exact advanced-indexing) variant — what XLA:CPU
    runs fastest. Its ``block[:, idx]`` column gather lowers to per-element
    loads on TPU (the pattern ``ops/stats.py`` measured at ~15 Melem/s);
    accelerators should use :func:`gather_submatrix_local_mxu` (the engine
    picks per ``EngineConfig.gather_mode``, same rule as the replicated
    path)."""
    rows_per = block.shape[0]
    start = jax.lax.axis_index(axis) * rows_per
    rel = idx - start
    in_block = (rel >= 0) & (rel < rows_per)
    safe = jnp.where(in_block, rel, 0)
    cols = block[:, idx]                       # (rows_per, m) local gather
    part = jnp.where(in_block[:, None], cols[safe, :], 0.0)  # (m, m)
    return jax.lax.psum(part, axis)


def gather_submatrix_local_mxu(
    block: jnp.ndarray, idx: jnp.ndarray, axis: str = ROW_AXIS
):
    """TPU-fast sharded submatrix gather: the sorted-row + one-hot-matmul
    technique of :func:`netrep_tpu.ops.stats.gather_submatrix_mxu` applied
    *inside* the shard_map (VERDICT r1 item 3 — the direct variant's
    column gather crawls on TPU):

    1. sort the indices ascending (DMA-friendly row order);
    2. local ROW gather from this device's (rows_per, n) block — rows owned
       by other shards are zeroed, not fetched;
    3. column select as a one-hot matmul riding the MXU → this shard's
       additive (m, m) contribution in the sorted basis;
    4. ``psum`` over the row axis assembles the full sorted submatrix —
       the collective moves only O(m²);
    5. rotate back to the original (discovery-paired) order with the
       permutation matmuls ``Pᵀ S P``.

    Value fidelity matches the replicated mxu path: selection matmuls are
    exact in exact arithmetic; on TPU the default-precision f32 matmul
    carries bf16 operand rounding (~4e-3 relative, attenuated ~1/m in the
    statistics — see EngineConfig.gather_mode)."""
    rows_per, n = block.shape
    m = idx.shape[-1]
    order = jnp.argsort(idx)
    idx_sorted = jnp.take(idx, order)
    pos = jax.lax.broadcasted_iota(jnp.int32, (m, m), 1)
    unsort = (pos == order[:, None]).astype(block.dtype)          # P (m, m)

    start = jax.lax.axis_index(axis) * rows_per
    rel = idx_sorted - start
    in_block = (rel >= 0) & (rel < rows_per)
    safe = jnp.clip(rel, 0, rows_per - 1)
    rows = jnp.where(in_block[:, None], block[safe, :], 0.0)      # (m, n)
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (n, m), 0)
    onehot = (col_ids == idx_sorted[None, :]).astype(block.dtype)  # (n, m)
    part = jnp.matmul(rows, onehot, preferred_element_type=jnp.float32)
    sub_sorted = jax.lax.psum(part, axis)
    return jnp.matmul(
        jnp.swapaxes(unsort, -1, -2),
        jnp.matmul(sub_sorted, unsort, preferred_element_type=jnp.float32),
        preferred_element_type=jnp.float32,
    )


def ring_chunk_specs(mesh_axis: str):
    """Shard_map spec contract of the ring-exchange fused-stats path
    (ISSUE 8; :mod:`netrep_tpu.ops.fused_stats`): the chunk splits over
    BOTH mesh axes — ``P((perm, row))`` on the permutation dimension, so
    each (perm, row) shard owns its own permutation slice — while the
    row-sharded matrices enter with their storage layout
    (``P(ROW_AXIS, None)``) and everything else replicates. Returns
    ``(combined_spec, op_specs)`` with ``op_specs`` matching the engines'
    ``chunk_args()`` tuple ``(pool, corr, net, dataT, discs)``; ONE
    definition shared by the materialized chunk builder and both
    streaming builders, so the three programs cannot drift in how they
    shard the ring."""
    combined = P((mesh_axis, ROW_AXIS))
    mat = P(ROW_AXIS, None)
    return combined, (P(), mat, mat, P(), P())


def gather_corr_net(gather, tc, tn, idx, net_beta):
    """Single dispatch point for derived-network mode over a sharded
    gatherer: with ``tn`` present, gather the (corr, net) submatrix pair;
    with ``tn`` None, gather only the correlation and derive the network on
    device via :func:`netrep_tpu.ops.stats.derived_net` — ``net_beta`` is
    that function's knob: a power β or a (β, kind) pair
    (EngineConfig.network_from_correlation).
    One helper so the observed, discovery-bucket, null-chunk, and multi-test
    paths cannot drift."""
    from ..ops import stats as jstats

    if tn is None:
        sub_c = gather(tc, None, idx)
        return sub_c, jstats.derived_net(sub_c, net_beta)
    return gather(tc, tn, idx)


def make_sharded_gatherer(
    mesh: Mesh,
    batch_axis: str | None = None,
    mode: str = "direct",
    perm_batch: int | None = None,
):
    """Build a ``shard_map``-wrapped batched gather over row-sharded
    correlation/network matrices.

    Returns ``gather(corr, net, idx)`` with ``idx`` (..., m) int32
    (arbitrary leading batch dims) → ``(sub_corr, sub_net)`` each
    (..., m, m). With ``batch_axis`` set (e.g. the permutation axis), the
    leading batch dim of ``idx`` and of the outputs stays sharded over that
    mesh axis — permutation data parallelism composes with row sharding on a
    2-D mesh, and each psum assembles only the local permutation shard's
    submatrices.

    ``mode`` selects the per-shard gather kernel: ``'direct'`` (exact
    advanced indexing — CPU) or ``'mxu'`` (sorted-row + one-hot matmuls —
    TPU; :func:`gather_submatrix_local_mxu`). ``perm_batch`` bounds the
    working set on 3-D ``(C, K, m)`` index batches: the local permutation
    axis is evaluated ``perm_batch`` at a time with ``lax.map`` inside the
    shard region (the mxu row buffers are (K·m, n) per permutation — at
    genome scale an unbatched chunk would not fit in HBM), mirroring the
    replicated path's ``EngineConfig.perm_batch``."""
    if mode not in ("direct", "mxu", "fused"):
        raise ValueError(
            f"mode must be 'direct', 'mxu', or 'fused', got {mode!r}"
        )
    if mode == "fused":
        # One-pass Pallas kernel per shard (ops/fused_gather): DMA only the
        # locally-owned rows, zero the rest, psum assembles — the kernel
        # batches arbitrary leading dims itself (its grid bounds the VMEM
        # working set), so no lax.map batching is needed here.
        from ..ops.fused_gather import gather_submatrix_fused_local

        interpret = jax.default_backend() == "cpu"

        def local_fused(block, idx_rep, axis=ROW_AXIS):
            rows_per = block.shape[0]
            start = jax.lax.axis_index(axis) * rows_per
            part = gather_submatrix_fused_local(
                block, idx_rep, start, interpret=interpret
            )
            return jax.lax.psum(part, axis)

        def body(corr_blk, net_blk, idx_rep):
            return local_fused(corr_blk, idx_rep), local_fused(net_blk, idx_rep)

        def body_single(blk, idx_rep):
            return local_fused(blk, idx_rep)

        idx_spec = P(batch_axis) if batch_axis else P()

        def gather(corr, net, idx):
            if net is None:
                return _shard_map(
                    body_single,
                    mesh=mesh,
                    in_specs=(P(ROW_AXIS, None), idx_spec),
                    out_specs=idx_spec,
                    **_NO_CHECK_KW,
                )(corr, idx)
            return _shard_map(
                body,
                mesh=mesh,
                in_specs=(P(ROW_AXIS, None), P(ROW_AXIS, None), idx_spec),
                out_specs=(idx_spec, idx_spec),
                **_NO_CHECK_KW,
            )(corr, net, idx)

        return gather

    local = (
        gather_submatrix_local if mode == "direct"
        else gather_submatrix_local_mxu
    )

    def batched(one, idx_rep):
        if idx_rep.ndim == 1:
            return one(idx_rep)
        over_mods = jax.vmap(one)
        if idx_rep.ndim == 2:
            return over_mods(idx_rep)
        if idx_rep.ndim == 3 and perm_batch is not None:
            # (C_local, K, m): bound the per-dispatch working set
            return jax.lax.map(over_mods, idx_rep, batch_size=perm_batch)
        fn = over_mods
        for _ in range(idx_rep.ndim - 2):
            fn = jax.vmap(fn)
        return fn(idx_rep)

    def body(corr_blk, net_blk, idx_rep):
        return batched(
            lambda ix: (local(corr_blk, ix), local(net_blk, ix)), idx_rep
        )

    def body_single(blk, idx_rep):
        return batched(lambda ix: local(blk, ix), idx_rep)

    idx_spec = P(batch_axis) if batch_axis else P()

    def gather(corr, net, idx):
        """``net=None`` gathers only the correlation submatrices (derived-
        network mode, EngineConfig.network_from_correlation) and returns a
        single array instead of a pair."""
        if net is None:
            return _shard_map(
                body_single,
                mesh=mesh,
                in_specs=(P(ROW_AXIS, None), idx_spec),
                out_specs=idx_spec,
            )(corr, idx)
        return _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(ROW_AXIS, None), P(ROW_AXIS, None), idx_spec),
            out_specs=(idx_spec, idx_spec),
        )(corr, net, idx)

    return gather
