"""Sparse permutation engine — Config E (BASELINE.json:11): permutation
nulls over kNN-graph adjacencies without ever materializing an ``n × n``
matrix. Same contract as :class:`~netrep_tpu.parallel.engine.
PermutationEngine` (bucketed static shapes, chunked/interruptible/
checkpointable null loop, chunk- and mesh-independent RNG), different data
plane: padded neighbor lists + on-the-fly correlation
(:mod:`netrep_tpu.ops.sparse`).

The reference has no sparse mode (SURVEY.md §2.3: its only scale axis is
dense ``n²`` matrices in shared memory); this engine is the rebuild's answer
to the survey's "sharded gather + masked reduction is this domain's context
parallelism" item for graphs whose adjacency is structurally sparse. The
working set per chunk is ``O(C·K·cap·k)`` — at Config E scale (n=50k,
k≈30) a 64-permutation chunk over 20 modules of ≤200 nodes is ~100 MB,
versus 10 GB for one dense adjacency.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import sparse as jsparse
from ..ops.oracle import N_STATS
from ..ops.sparse import SparseAdjacency
from ..utils.config import EngineConfig
from .engine import ModuleSpec, PermutationEngine, run_checkpointed_chunks


class _SparseBucket:
    def __init__(self, cap, module_pos, disc, obs_idx, slices):
        self.cap = cap
        self.module_pos = module_pos
        self.disc = disc
        self.obs_idx = obs_idx
        self.slices = slices


class SparsePermutationEngine:
    """Permutation-null engine for one (discovery, test) pair of sparse
    networks.

    Parameters
    ----------
    disc_adj, test_adj : :class:`~netrep_tpu.ops.sparse.SparseAdjacency`.
    disc_data, test_data : (n_samples, n) data matrices or None. Without
        data, a precomputed sparse correlation (``disc_corr``/``test_corr``
        below) keeps four statistics finite; with neither, only
        ``avg.weight`` and ``cor.degree`` are defined (see
        :mod:`netrep_tpu.ops.sparse` on why sparse data-less differs from
        dense data-less).
    modules : ordered :class:`ModuleSpec` list (discovery/test index pairs).
    pool : candidate test-node ids the null samples from (SURVEY.md §3.1).
    config, mesh : as for :class:`PermutationEngine`; ``mesh`` shards the
        permutation axis (``config.mesh_axis``) — the adjacency itself is
        replicated (n·k floats is small by construction).
    """

    def __init__(
        self,
        disc_adj: SparseAdjacency,
        disc_data,
        test_adj: SparseAdjacency,
        test_data,
        modules: Sequence[ModuleSpec],
        pool: np.ndarray,
        config: EngineConfig = EngineConfig(),
        mesh=None,
        disc_corr: SparseAdjacency | None = None,
        test_corr: SparseAdjacency | None = None,
    ):
        """``disc_corr``/``test_corr`` are optional PRECOMPUTED sparse
        correlations (same neighbor-list format as the adjacency): they feed
        the correlation statistics instead of the on-the-fly ``zᵀz`` — and
        in the data-less case restore cor.cor/avg.cor for topology-only
        users (VERDICT r1 item 8)."""
        if config.matrix_sharding == "row":
            raise NotImplementedError(
                "matrix_sharding='row' does not apply to the sparse engine: "
                "the padded neighbor lists are O(n·k) and are replicated"
            )
        self.config = config
        self.mesh = mesh
        self.modules = list(modules)
        self.n_modules = len(self.modules)
        self.has_data = disc_data is not None and test_data is not None

        bad = [m.label for m in self.modules if m.size < 2]
        if bad:
            raise ValueError(
                f"modules {bad} have fewer than 2 nodes present in the test "
                "dataset; drop them before building the engine"
            )

        dtype = jnp.dtype(config.dtype)
        self._nbr = jnp.asarray(test_adj.nbr)
        self._wgt = jnp.asarray(test_adj.wgt, dtype)
        self._test_data = (
            jnp.asarray(test_data, dtype) if self.has_data else None
        )
        self.has_corr = disc_corr is not None and test_corr is not None
        if (disc_corr is None) != (test_corr is None):
            raise ValueError(
                "provide both disc_corr and test_corr sparse correlations, "
                "or neither"
            )
        if self.has_corr:
            for what, c, adj in (("disc", disc_corr, disc_adj),
                                 ("test", test_corr, test_adj)):
                if not isinstance(c, SparseAdjacency) or c.n != adj.n:
                    raise ValueError(
                        f"{what}_corr must be a SparseAdjacency over the "
                        f"same {adj.n} nodes as the {what} network"
                    )
            self._cnbr = jnp.asarray(test_corr.nbr)
            self._cwgt = jnp.asarray(test_corr.wgt, dtype)
        else:
            self._cnbr = self._cwgt = None
        self.pool = np.asarray(pool, dtype=np.int32)
        self.total_take = sum(m.size for m in self.modules)
        if self.total_take > self.pool.size:
            raise ValueError(
                f"total module size ({self.total_take}) exceeds the "
                f"candidate pool ({self.pool.size}); use null='all' or drop "
                "modules"
            )
        self._pool_dev = jnp.asarray(self.pool)

        # bucket modules by padded capacity so each bucket compiles once
        # (SURVEY.md §7 "Variable module sizes vs. XLA static shapes")
        disc_nbr = jnp.asarray(disc_adj.nbr)
        disc_wgt = jnp.asarray(disc_adj.wgt, dtype)
        disc_cnbr = jnp.asarray(disc_corr.nbr) if self.has_corr else None
        disc_cwgt = (
            jnp.asarray(disc_corr.wgt, dtype) if self.has_corr else None
        )
        disc_data_dev = (
            jnp.asarray(disc_data, dtype) if self.has_data else None
        )
        by_cap: dict[int, list[int]] = {}
        for k, m in enumerate(self.modules):
            by_cap.setdefault(config.rounded_cap(m.size), []).append(k)

        offsets = np.concatenate(
            [[0], np.cumsum([m.size for m in self.modules])]
        ).astype(int)

        self.buckets: list[_SparseBucket] = []
        for cap, pos in sorted(by_cap.items()):
            K = len(pos)
            disc_idx = np.zeros((K, cap), dtype=np.int32)
            obs_idx = np.zeros((K, cap), dtype=np.int32)
            mask = np.zeros((K, cap), dtype=np.float32)
            slices = []
            for row, k in enumerate(pos):
                m = self.modules[k]
                sz = m.size
                disc_idx[row, :sz] = np.asarray(m.disc_idx, dtype=np.int32)
                obs_idx[row, :sz] = np.asarray(m.test_idx, dtype=np.int32)
                mask[row, :sz] = 1.0
                slices.append((int(offsets[k]), sz))
            disc = jsparse.make_disc_props_sparse(
                disc_nbr, disc_wgt, disc_data_dev,
                jnp.asarray(disc_idx), jnp.asarray(mask),
                corr_nbr=disc_cnbr,
                corr_wgt=disc_cwgt,
            )
            self.buckets.append(
                _SparseBucket(cap, pos, disc, jnp.asarray(obs_idx), slices)
            )

        self._chunk_fn_cached: Callable | None = None
        self._observed_fn = None

    # shared chunk/key contract — single source of truth on the dense engine
    effective_chunk = PermutationEngine.effective_chunk
    perm_keys = staticmethod(PermutationEngine.perm_keys)

    def fingerprint_arrays(self):
        arrays = [self._nbr, self._wgt, self._test_data,
                  self._cnbr, self._cwgt]
        for b in self.buckets:
            arrays.extend(
                f for f in b.disc if f is not None and hasattr(f, "reshape")
            )
        return arrays

    def observed(self) -> np.ndarray:
        """(n_modules, 7) observed statistics on the actual overlap sets."""
        if self._observed_fn is None:
            self._observed_fn = jax.jit(
                jax.vmap(
                    partial(
                        jsparse.sparse_gather_and_stats,
                        n_iter=self.config.power_iters,
                        summary_method="eigh",  # observed: exact, runs once
                    ),
                    in_axes=(0, 0, None, None, None, None, None),
                )
            )
        out = np.full((self.n_modules, N_STATS), np.nan)
        for b in self.buckets:
            res = self._observed_fn(
                b.disc, b.obs_idx, self._nbr, self._wgt, self._test_data,
                self._cnbr, self._cwgt,
            )
            out[b.module_pos] = np.asarray(res, dtype=np.float64)
        return out

    def chunk_args(self) -> tuple:
        """Device operands, passed to the jitted chunk as arguments (not
        closure captures — captured device arrays become compile-time
        constants; see :meth:`PermutationEngine.chunk_args`)."""
        return (
            self._pool_dev, self._nbr, self._wgt, self._test_data,
            self._cnbr, self._cwgt,
            [b.disc for b in self.buckets],
        )

    def chunk_body(self) -> Callable:
        """Unjitted chunk program; same permutation-draw semantics as the
        dense engine (one pool shuffle per permutation, consecutive module
        slices — disjoint node sets within a permutation). Signature:
        ``chunk(keys, *chunk_args)``."""
        cfg = self.config
        caps_slices = [(b.cap, tuple(b.slices)) for b in self.buckets]

        def chunk(keys: jax.Array, pool, nbr, wgt, td, cnbr, cwgt, discs) -> list[jax.Array]:
            perm = jax.vmap(lambda k: jax.random.permutation(k, pool))(keys)
            outs = []
            for (cap, slices), disc in zip(caps_slices, discs):
                cols = []
                for off, size in slices:
                    idx = perm[:, off: off + size]
                    idx = jnp.pad(idx, ((0, 0), (0, cap - size)))
                    cols.append(idx)
                idx_b = jnp.stack(cols, axis=1)  # (C, K, cap)
                inner = jax.vmap(
                    partial(
                        jsparse.sparse_gather_and_stats,
                        n_iter=cfg.power_iters,
                        summary_method=cfg.summary_method,
                    ),
                    in_axes=(0, 0, None, None, None, None, None),
                )
                over_perms = jax.vmap(
                    inner, in_axes=(None, 0, None, None, None, None, None)
                )
                outs.append(over_perms(disc, idx_b, nbr, wgt, td, cnbr, cwgt))
            return outs

        return chunk

    def _chunk_fn(self) -> Callable:
        if self._chunk_fn_cached is None:
            chunk = self.chunk_body()
            args = self.chunk_args()
            if self.mesh is not None:
                ksh = NamedSharding(self.mesh, P(self.config.mesh_axis))
                osh = [
                    NamedSharding(self.mesh, P(self.config.mesh_axis))
                    for _ in self.buckets
                ]
                from .distributed import to_global

                jitted = jax.jit(chunk, out_shardings=osh)
                self._chunk_fn_cached = lambda keys: jitted(
                    to_global(keys, ksh), *args
                )
            else:
                jitted = jax.jit(chunk)
                self._chunk_fn_cached = lambda keys: jitted(keys, *args)
        return self._chunk_fn_cached

    def run_null(
        self,
        n_perm: int,
        key: jax.Array | int = 0,
        progress: Callable[[int, int], None] | None = None,
        nulls_init: np.ndarray | None = None,
        start_perm: int = 0,
        checkpoint_path: str | None = None,
        checkpoint_every: int = 8192,
    ) -> tuple[np.ndarray, int]:
        """Same contract as :meth:`PermutationEngine.run_null` (chunked,
        interruptible, resumable, checkpointable; same-seed ⇒ same null)."""

        def write(nulls, outs, done, take):
            from .distributed import gather_to_host

            for b, out in zip(self.buckets, outs):
                # full-chunk transfer, host-side slice (device slicing is an
                # eager op — ~1s dispatch on tunneled backends); cross-host
                # allgather on multi-host meshes
                arr = gather_to_host(out).astype(np.float64)
                nulls[done: done + take, b.module_pos] = arr[:take]

        return run_checkpointed_chunks(
            self, n_perm, key, self._chunk_fn(),
            (n_perm, self.n_modules, N_STATS), write,
            progress=progress, nulls_init=nulls_init, start_perm=start_perm,
            checkpoint_path=checkpoint_path, checkpoint_every=checkpoint_every,
        )
